"""Process entry point: config, metrics, store, recursion, server wiring.

Port of the reference's ``main.js`` startup pipeline (``main.js:154-224``):

    metrics server (port+1000) → store client + mirror cache → recursion
    (optional) → balancer-socket SIGTERM handling → DNS server

Run as:  python -m binder_tpu.main -f etc/config.json [-p port] [-v]
"""
from __future__ import annotations

import asyncio
import logging
import os
import signal
import sys
from typing import Dict

from binder_tpu.config.options import ConfigError, parse_options
from binder_tpu.introspect import (BalancerStatsFold, FlightRecorder,
                                   Introspector, LoopLagWatchdog)
from binder_tpu.metrics.collector import MetricsCollector, MetricsServer
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache
from binder_tpu.utils import netif
from binder_tpu.utils.jsonlog import log_event, make_logger

NAME = "binder"


def safe_unlink(path: str, log: logging.Logger) -> None:
    try:
        os.unlink(path)
    except FileNotFoundError:
        pass
    except OSError as e:
        log.warning("unlinking socket path %s: %s", path, e)


def make_store(options: Dict[str, object], log: logging.Logger,
               collector=None, recorder=None):
    """Select the coordination-store backend from config."""
    store_cfg = options.get("store") or {}
    backend = store_cfg.get("backend", "zookeeper")
    if backend == "fake":
        store = FakeStore(recorder=recorder)
        fixture = store_cfg.get("fixture")
        if fixture:
            import json
            with open(fixture) as f:
                for path, obj in json.load(f).items():
                    store.put_json(path, obj)
        synthetic = store_cfg.get("synthetic")
        if synthetic:
            # zone_scale bench / smoke surface: generate a
            # production-scale zone procedurally instead of shipping a
            # hundred-MB fixture file through JSON twice
            from binder_tpu.store.fake import populate_synthetic
            n = populate_synthetic(
                store, str(options["dnsDomain"]),
                hosts=int(synthetic.get("hosts", 0)),
                racks=int(synthetic.get("racks", 0)),
                subtree=str(synthetic.get("subtree", "zs")))
            log.info("synthetic zone: %d host(s) generated", n)
        store.start_session()
        return store
    if backend == "zookeeper":
        try:
            from binder_tpu.store.zk_client import ZKClient
        except ImportError as e:
            raise ConfigError(f"zookeeper store backend unavailable: {e}")
        return ZKClient(
            address=store_cfg.get("host",
                                  os.environ.get("ZK_HOST", "127.0.0.1")),
            port=int(store_cfg.get("port", 2181)),
            session_timeout_ms=int(store_cfg.get("sessionTimeout", 30000)),
            log=log,
            collector=collector,
            recorder=recorder,
        )
    raise ConfigError(f"unknown store backend: {backend}")


async def run_supervisor(options: Dict[str, object]):
    """Shard mode (``--shards N``): this process is the mirror OWNER —
    it holds the one store session, fans mutations out to N serving
    workers over per-shard socketpair mutation logs, respawns crashes,
    drains on SIGTERM, and aggregates metrics/status.  It serves no
    queries itself; the kernel balances those across the workers'
    SO_REUSEPORT sockets (binder_tpu/shard, docs/operations.md)."""
    from binder_tpu.shard import ShardSupervisor

    log = make_logger(NAME, str(options.get("logLevel", os.environ.get(
        "LOG_LEVEL", "info"))))
    log_event(log, logging.INFO, "starting shard supervisor", options={
        k: v for k, v in options.items() if k != "store"})

    port = int(options["port"])
    collector = MetricsCollector(static_labels={
        "datacenter": options.get("datacenterName"),
        "instance": options.get("instance_uuid"),
        "server": options.get("server_uuid"),
        "service": options.get("service_name"),
        "port": port,
    })
    metrics = MetricsServer(collector, address="0.0.0.0",
                            port=port + 1000 if port else 0)
    metrics.start()
    log.info("metrics server started on port %d", metrics.port)

    recorder = FlightRecorder(
        capacity=int(options.get("flightRecorderSize", 512)), log=log)
    store = make_store(options, log, collector=collector,
                       recorder=recorder)
    cache = MirrorCache(store, str(options["dnsDomain"]), log=log,
                        collector=collector, recorder=recorder)
    supervisor = ShardSupervisor(options=options, store=store,
                                 cache=cache, collector=collector,
                                 recorder=recorder, log=log, name=NAME)
    # arm /status before start(): the canonical announce line prints
    # inside start() once the whole group serves, and a harness may
    # poll the snapshot the instant it sees that line (the metrics
    # server thread answers concurrently with the lines below)
    metrics.status_source = supervisor.snapshot
    await supervisor.start()

    loop = asyncio.get_running_loop()

    def on_sigterm():
        log.info("caught SIGTERM; draining %d shard(s)", supervisor.n)

        async def _drain():
            await supervisor.drain()
            os._exit(0)

        loop.create_task(_drain())

    loop.add_signal_handler(signal.SIGTERM, on_sigterm)

    def on_sighup():
        # zero-downtime rolling operations (docs/operations.md
        # "Rolling upgrade / config reload"): re-read the config file
        # and drain-and-replace one shard at a time; a roll already in
        # progress absorbs the repeat signal
        log.info("caught SIGHUP; rolling %d shard(s) with reloaded "
                 "config", supervisor.n)
        supervisor.request_roll(reload_config=True)

    loop.add_signal_handler(signal.SIGHUP, on_sighup)

    # chaos (supervisor-side): store faults and watch storms hit the
    # owner mirror and propagate down every mutation log; shard-kill
    # SIGKILLs a worker mid-load; stream faults drive the shared
    # reuseport TCP port (whichever worker the kernel picks)
    chaos_cfg = options.get("chaos")
    if chaos_cfg:
        from binder_tpu.chaos import ChaosDriver, FaultPlan
        from binder_tpu.store.cache import domain_to_path
        plan = FaultPlan.parse(str(chaos_cfg.get("plan", "")),
                               seed=int(chaos_cfg.get("seed", 0)))
        domain = str(options["dnsDomain"])

        def chaos_mutate(i: int) -> None:
            store.put_json(
                domain_to_path(f"chaos{i % 8}.{domain}"),
                {"type": "host",
                 "host": {"address": f"10.254.{i % 8}.{i % 250 + 1}"}})

        chaos_host = str(options.get("host", "0.0.0.0"))
        if chaos_host in ("0.0.0.0", "::"):
            chaos_host = "127.0.0.1"
        driver = ChaosDriver(
            plan, store=store,
            mutate=chaos_mutate if hasattr(store, "put_json") else None,
            tcp_target=(chaos_host, supervisor.tcp_port,
                        f"chaos0.{domain}"),
            udp_target=(chaos_host, supervisor.udp_port,
                        f"chaos0.{domain}"),
            shard_target=supervisor.kill_shard,
            # worker-roll is the cooperative counterpart to shard-kill:
            # drain-and-replace with zero query loss, mid-incident
            roll_target=lambda shard=-1: supervisor.request_roll(
                shard=shard),
            # skew-replica desyncs one worker's mutation log (the
            # digest frames must catch it); the supervisor owns the
            # per-link streams
            verify_target=supervisor,
            recorder=recorder, log=log)
        supervisor.chaos_driver = driver
        driver.start()
        log.warning("chaos: FaultPlan armed (%d scheduled action(s), "
                    "%.1fs)", len(plan.timeline), plan.duration)

    watchdog = LoopLagWatchdog(collector=collector, recorder=recorder)
    watchdog.start()
    recorder.install_sigusr2(loop, path=options.get("flightRecorderDump"))
    supervisor.watchdog = watchdog
    supervisor.metrics = metrics
    log.info("done with binder init (shard supervisor)")
    return supervisor


def resolve_shard_count(options: Dict[str, object]) -> int:
    """``shards: "auto"`` sizes the reuseport group to the machine —
    one single-threaded worker per core is the sizing rule
    (docs/operations.md "Sizing N")."""
    n = options.get("shards") or 0
    if n == "auto":
        n = os.cpu_count() or 1
    return int(n)


async def run(options: Dict[str, object]) -> BinderServer:
    shard_worker = options.get("shardWorker")
    # resolve "auto" up front so the supervisor and its status
    # plumbing only ever see an int
    options["shards"] = resolve_shard_count(options)
    if shard_worker is None and options["shards"] >= 1:
        return await run_supervisor(options)

    log = make_logger(NAME, str(options.get("logLevel", os.environ.get(
        "LOG_LEVEL", "info"))))
    log_event(log, logging.INFO, "starting with options", options={
        k: v for k, v in options.items() if k != "store"})

    port = int(options["port"])
    collector = MetricsCollector(static_labels={
        "datacenter": options.get("datacenterName"),
        "instance": options.get("instance_uuid"),
        "server": options.get("server_uuid"),
        "service": options.get("service_name"),
        "port": port,
    })
    # a shard worker's scrape endpoint is per-process (ephemeral port,
    # reported to the supervisor in the hello frame); the well-known
    # port+1000 belongs to the supervisor's aggregated view
    metrics = MetricsServer(collector, address="0.0.0.0",
                            port=(0 if shard_worker is not None
                                  else port + 1000 if port else 0))
    metrics.start()
    log.info("metrics server started on port %d", metrics.port)

    recorder = FlightRecorder(
        capacity=int(options.get("flightRecorderSize", 512)), log=log)
    if shard_worker is not None:
        # shard worker: NO store session of its own — the one session
        # lives in the supervisor; this process replays the mutation
        # log (snapshot now, deltas once the loop runs)
        from binder_tpu.shard import ReplicaStore
        from binder_tpu.shard.protocol import SHARD_FD_ENV
        fd = int(os.environ[SHARD_FD_ENV])
        store = ReplicaStore.from_fd(fd, int(shard_worker),
                                     recorder=recorder, log=log)
        nodes = store.read_snapshot()
        log.info("shard %d: snapshot applied (%d node(s))",
                 shard_worker, nodes)
    else:
        store = make_store(options, log, collector=collector,
                           recorder=recorder)
    cache = MirrorCache(store, str(options["dnsDomain"]), log=log,
                        collector=collector, recorder=recorder)

    # multi-DC federation (binder_tpu/federation, docs/federation.md):
    # peer discovery from the watched /dcs subtree, cross-DC forwarding
    # through the recursion plane, foreign-answer stale-serve.  Started
    # before the recursion client so its registry already holds the
    # current membership when the routing table first fills.
    federation = None
    fed_cfg = options.get("federation")
    if fed_cfg:
        from binder_tpu.federation import Federation
        federation = Federation(
            store=store, dns_domain=str(options["dnsDomain"]),
            datacenter_name=str(options.get("datacenterName", "")),
            config=dict(fed_cfg), collector=collector,
            recorder=recorder, log=log)
        federation.start()

    recursion = None
    if options.get("recursion") or federation is not None:
        try:
            from binder_tpu.recursion import Recursion
        except ImportError as e:
            raise ConfigError(f"recursion unavailable: {e}")
        rcfg = dict(options.get("recursion") or {})
        # federation supplies the routing table from its /dcs registry
        # unless the recursion block brings its own discovery (static
        # dcs or UFDS).  Self-exclusion is then by DC name in the
        # registry, not by NIC address — federated peers may share a
        # host (one port per DC), which the NIC filter would wrongly
        # drop; nicSelfFilter: true restores the address filter.
        fed_source = None
        if federation is not None and not (rcfg.get("dcs")
                                           or rcfg.get("ufds")):
            fed_source = federation.resolver_source()
        recursion = Recursion(
            zk_cache=cache, log=log,
            region_name=rcfg.get("regionName", ""),
            datacenter_name=str(options.get("datacenterName", "")),
            dns_domain=str(options["dnsDomain"]),
            source=fed_source,
            # static per-DC resolver lists may live at recursion.dcs or
            # recursion.ufds.dcs; a real UFDS/LDAP source plugs in here
            ufds=rcfg.get("ufds") or rcfg,
            nic_provider=((lambda: [])
                          if fed_source is not None
                          and not (fed_cfg or {}).get("nicSelfFilter")
                          else netif.local_addresses),
            # per-peer circuit breakers report binder_breaker_state and
            # breaker-transition flight events (docs/degradation.md)
            collector=collector, recorder=recorder,
        )
        if federation is not None:
            federation.attach(recursion)
        await recursion.wait_ready()

    balancer_socket = (None if shard_worker is not None
                       else options.get("balancerSocket"))
    if balancer_socket:
        # clear any stale socket; unlink on SIGTERM so the balancer stops
        # routing to us (main.js:181-199)
        safe_unlink(str(balancer_socket), log)
        loop = asyncio.get_running_loop()

        def on_sigterm():
            log.info("caught SIGTERM; unlinking socket %s", balancer_socket)
            safe_unlink(str(balancer_socket), log)
            sys.exit(0)

        loop.add_signal_handler(signal.SIGTERM, on_sigterm)

    server = BinderServer(
        zk_cache=cache,
        dns_domain=str(options["dnsDomain"]),
        datacenter_name=str(options.get("datacenterName", "")),
        recursion=recursion,
        log=log,
        collector=collector,
        name=NAME,
        host=str(options.get("host", "0.0.0.0")),
        port=port,
        balancer_socket=str(balancer_socket) if balancer_socket else None,
        query_log=bool(options.get("queryLog", True)),
        cache_size=int(options.get("size", 10000)),
        cache_expiry_ms=int(options.get("expiry", 60000)),
        zone_precompile=bool(options.get("zonePrecompile", True)),
        answer_precompile=bool(options.get("answerPrecompile", True)),
        precompile_size=(int(options["precompileSize"])
                         if "precompileSize" in options else None),
        tcp_idle_timeout=(float(options["tcpIdleTimeout"])
                          if "tcpIdleTimeout" in options else None),
        max_tcp_conns=(int(options["maxTcpConns"])
                       if "maxTcpConns" in options else None),
        max_tcp_write_buffer=(int(options["maxTcpWriteBuffer"])
                              if "maxTcpWriteBuffer" in options else None),
        flight_recorder=recorder,
        # graceful degradation + overload shedding (docs/degradation.md):
        # on by default in production, tunable/disable-able per block
        # ({"enabled": false} turns one off)
        degradation=dict(options.get("degradation") or {}),
        admission=dict(options.get("admission") or {}),
        # response rate limiting at the UDP ingress (hostile-internet
        # posture, docs/operations.md): same on-by-default convention
        rrl=dict(options.get("rrl") or {}),
        # serving-plane verification + propagation tracing
        # (docs/observability.md): on by default like the other
        # production observability
        verify=dict(options.get("verify") or {}),
        # shard workers share ONE port via SO_REUSEPORT (the kernel
        # balances) and leave the canonical announce lines to the
        # supervisor, which prints them once the whole group serves
        reuse_port=shard_worker is not None,
        announce=shard_worker is None,
    )
    # introspection handle (/status federation section, bstat line)
    server.federation = federation
    await server.start()

    if len(cache.nodes) > 100_000:
        # large zones: the mirror is millions of long-lived objects; a
        # gen-2 GC pass over them is a multi-hundred-ms serving stall
        # for zero reclaim.  Freeze the resident set out of collection
        # (query/mutation garbage still collects normally).  Runs
        # BEFORE the loop-lag watchdog arms — the collect+freeze pass
        # is itself a one-time stall-sized pause.
        import gc
        gc.collect()
        gc.freeze()
        log.info("large zone: froze %d mirrored names out of gc",
                 len(cache.nodes))

    # fault injection (chaos) — ONLY when configured, for soaks and the
    # bench's degraded axis: a scripted FaultPlan drives session loss /
    # watch storms / loop stalls inside the live process
    # (binder_tpu/chaos, docs/degradation.md).  In shard mode the
    # supervisor owns chaos (it has the store and the kill switch).
    chaos_cfg = None if shard_worker is not None \
        else options.get("chaos")
    if chaos_cfg:
        from binder_tpu.chaos import ChaosDriver, FaultPlan
        from binder_tpu.store.cache import domain_to_path
        plan = FaultPlan.parse(str(chaos_cfg.get("plan", "")),
                               seed=int(chaos_cfg.get("seed", 0)))
        domain = str(options["dnsDomain"])

        def chaos_mutate(i: int) -> None:
            # default watch-storm mutator: churn a small ring of
            # chaos-owned host records under the served domain
            store.put_json(
                domain_to_path(f"chaos{i % 8}.{domain}"),
                {"type": "host",
                 "host": {"address": f"10.254.{i % 8}.{i % 250 + 1}"}})

        chaos_host = str(options.get("host", "0.0.0.0"))
        if chaos_host in ("0.0.0.0", "::"):
            chaos_host = "127.0.0.1"
        driver = ChaosDriver(
            plan, store=store,
            mutate=chaos_mutate if hasattr(store, "put_json") else None,
            # stream faults (tcp-slow-reader / tcp-half-close /
            # tcp-rst) drive the server's own TCP listener
            tcp_target=(chaos_host, server.tcp_port,
                        f"chaos0.{domain}"),
            udp_target=(chaos_host, server.udp_port,
                        f"chaos0.{domain}"),
            # verify-plane corruption (corrupt-answer / drop-reverse)
            # mutates the server's own tables behind the checker's back
            verify_target=server,
            recorder=recorder, log=log)
        server.chaos_driver = driver
        driver.start()
        log.warning("chaos: FaultPlan armed (%d scheduled action(s), "
                    "%.1fs)", len(plan.timeline), plan.duration)

    # introspection layer: loop-lag watchdog, status endpoint, SIGUSR2
    # flight-recorder dump, balancer stats fold (docs/observability.md)
    loop = asyncio.get_running_loop()
    watchdog = LoopLagWatchdog(collector=collector, recorder=recorder)
    watchdog.start()
    introspector = Introspector(server=server, recorder=recorder,
                                watchdog=watchdog, collector=collector,
                                name=NAME)
    introspector.set_loop(loop)
    metrics.status_source = introspector.snapshot
    recorder.install_sigusr2(
        loop, path=options.get("flightRecorderDump"))
    if balancer_socket:
        # the balancer serves its stats as a sibling socket in the same
        # directory (docs/balancer-protocol.md)
        BalancerStatsFold(collector, os.path.join(
            os.path.dirname(str(balancer_socket)), ".balancer.stats"),
            log=log)
    server.watchdog = watchdog          # keep handles for shutdown /
    server.introspector = introspector  # debugging sessions

    if shard_worker is not None:
        _wire_shard_worker(server, store, metrics, collector,
                           int(shard_worker), loop, log)

    log.info("done with binder init")
    server.metrics = metrics  # keep a handle for shutdown
    return server


def _wire_shard_worker(server: BinderServer, store, metrics, collector,
                       shard: int, loop, log: logging.Logger) -> None:
    """Post-start plumbing for a shard worker: switch the mutation log
    to event-loop delta reading, report hello (pid + bound ports) to
    the supervisor, start the 1 Hz stats feed, drain on SIGTERM, and
    die if the supervisor link ever drops (an orphan worker would
    serve a silently aging mirror forever — the exact failure this
    architecture exists to avoid)."""
    from binder_tpu.shard import protocol

    def link_down():
        log.error("shard %d: supervisor gone; exiting", shard)
        os._exit(1)

    store.on_link_down = link_down
    verify = getattr(server, "_verify", None)
    if verify is not None:
        # replica-parity wiring: delta-frame trace contexts feed the
        # worker's propagation tracer, digest comparisons feed its
        # replica-digest counters (the supervisor counts its own half)
        store.tracer = verify.tracer
        store.on_digest = verify.note_digest
    store.start(loop)
    store.send(protocol.hello_frame(
        shard, os.getpid(), server.udp_port, server.tcp_port,
        metrics.port))
    requests = collector.counter("binder_requests_completed")

    async def stats_loop():
        while True:
            await asyncio.sleep(1.0)
            try:
                collector.fold()   # natively counted serves included
                rrl = getattr(server, "_rrl", None)
                adm = getattr(server, "_admission", None)
                store.send(protocol.stats_frame(
                    requests.total(), server.zk_cache.gen,
                    server.zk_cache.epoch, server.zk_cache.is_ready(),
                    len(server.engine.inflight),
                    rrl_dropped=(rrl.dropped if rrl is not None else 0),
                    shed=(sum(adm.shed_counts.values())
                          if adm is not None else 0)))
            except Exception:
                log.exception("shard stats report failed")

    server._shard_stats_task = loop.create_task(stats_loop())

    def on_sigterm():
        log.info("shard %d: caught SIGTERM; draining", shard)

        async def _drain():
            # rolling-drain semantics (docs/operations.md "Rolling
            # upgrade"): leave the reuseport group and serve out the
            # in-flight queries BEFORE tearing the serve stack down —
            # stop() cancels whatever quiesce could not finish
            try:
                pending = await server.engine.quiesce()
                if pending:
                    log.warning("shard %d: %d in-flight quer(ies) "
                                "unfinished at the drain deadline",
                                shard, pending)
                else:
                    log.info("shard %d: quiesced clean (in-flight "
                             "served out)", shard)
            except Exception:
                log.exception("shard %d: quiesce failed", shard)
            await server.stop()
            metrics.stop()
            os._exit(0)

        loop.create_task(_drain())

    loop.add_signal_handler(signal.SIGTERM, on_sigterm)


def main(argv=None) -> None:
    try:
        options = parse_options(argv)
    except ConfigError as e:
        print(e, file=sys.stderr)
        sys.exit(1)

    async def _run():
        await run(options)
        await asyncio.Event().wait()  # serve forever

    try:
        asyncio.run(_run())
    except KeyboardInterrupt:
        pass
    except ConfigError as e:
        print(e, file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
