"""Multi-datacenter federation: N binder clusters serving one namespace.

The reference's L5 does best-effort cross-DC resolution by forwarding
foreign names to binders in other datacenters, discovering those peers
through UFDS (``lib/recursion.js``).  This package is the rebuild's
multi-cluster layer:

- :mod:`binder_tpu.federation.registry` — peer discovery from a watched
  ``/dcs`` subtree in the coordination store (DC records carry name,
  zone cuts, and peer addresses; membership changes propagate like any
  other store mutation).
- :mod:`binder_tpu.federation.federation` — the serving-plane half:
  routes foreign names through the existing recursion client
  (breaker-filtered, hedged, budgeted, single-flighted), keeps a
  foreign-answer cache, and serves stale under the degradation policy
  when the owning DC is dark (TTL-clamped, withheld past the staleness
  cap — never a timeout).
"""
from binder_tpu.federation.federation import Federation
from binder_tpu.federation.registry import DcRegistry

__all__ = ["Federation", "DcRegistry"]
