"""DC membership from a watched ``/dcs`` subtree.

The reference discovers other datacenters' binders through UFDS
(``sdc-ldap search objectclass=resolver``, ``lib/recursion.js:202-219``)
— a second coordination system bolted onto the first.  Here the store we
already watch carries the membership: each child of ``/dcs`` is one
datacenter record,

    /dcs/<dc-name>  ->  {"zones": ["east", ...],        # zone cuts owned
                         "peers": ["10.0.0.1:53", ...]}  # its binders

and membership changes propagate exactly like any other mutation — the
children watcher sees a DC join or leave, the data watcher sees its peer
set change, and the registry pushes the new map to whoever registered a
change callback (the Federation, which refreshes the recursion routing
table immediately rather than waiting for the 5-minute discovery poll).

Zone-cut labels are the datacenter labels of the qname routing scheme:
a DC whose record says ``"zones": ["east"]`` is authoritative for
``*.east.<dnsDomain>``.
"""
from __future__ import annotations

import inspect
import json
import logging
import time
from typing import Callable, Dict, List, Optional

DCS_PATH = "/dcs"


class DcRegistry:
    """Watches ``/dcs`` and keeps the live DC-record map.

    Works against any :class:`~binder_tpu.store.interface.StoreClient`:
    delivery is purely push-based (children watcher on ``/dcs``, data
    watcher per child), so the fake store's synchronous events and real
    ZooKeeper's async ones both land here — including shard
    ``ReplicaStore`` workers, whose ``/dcs`` subtree is fanned through
    the supervisor's mutation log (``pnode``/``pgone`` frames) so a
    worker sees a DC join or leave exactly like the owner does.
    ``static_records`` seeds the map for config-pinned membership
    (deployments whose store carries no ``/dcs`` at all).
    """

    def __init__(self, store, *, self_name: str, path: str = DCS_PATH,
                 static_records: Optional[List[dict]] = None,
                 log: Optional[logging.Logger] = None,
                 recorder=None) -> None:
        self.store = store
        self.path = "/" + path.strip("/") if path.strip("/") else DCS_PATH
        self.self_name = self_name
        self.log = log or logging.getLogger("binder.federation")
        self.recorder = recorder
        #: dc name -> {"name", "zones", "peers"} (normalized)
        self.records: Dict[str, dict] = {}
        self._watched: set = set()
        self._cbs: List[Callable[[], None]] = []
        self.last_event_mono: Optional[float] = None
        self.joins = 0
        self.leaves = 0
        self._started = False
        for rec in (static_records or []):
            name = str(rec.get("name", "")) or None
            if name is None:
                continue
            norm = self._normalize(name, json.dumps(rec).encode("utf-8"))
            if norm is not None:
                self.records[name] = norm

    # -- lifecycle --

    def start(self) -> None:
        """Attach the watches.  Current state (if the store is
        connected and ``/dcs`` exists) is delivered synchronously by
        the watcher contract; later sessions resync via on_session."""
        if self._started:
            return
        self._started = True
        self.store.watcher(self.path).on("children", self._on_children)
        self.store.on_session(self._resync)

    def on_change(self, cb: Callable[[], None]) -> None:
        self._cbs.append(cb)

    # -- event plumbing --

    def _resync(self) -> None:
        """Session (re-)establishment: pull current state when the
        store reads synchronously (FakeStore family).  Real ZooKeeper
        re-delivers through its re-registered watches instead; its
        getters are coroutines and are skipped here."""
        get_children = getattr(self.store, "get_children", None)
        get_data = getattr(self.store, "get_data", None)
        if (not callable(get_children) or not callable(get_data)
                or inspect.iscoroutinefunction(get_children)):
            return
        kids = get_children(self.path)
        if kids is None:
            # /dcs absent (or the store went dark): keep what we have —
            # a local-session blip must not evict the membership map
            return
        self._on_children(kids)
        for k in kids:
            data = get_data(self.path + "/" + k)
            if data is not None:
                self._on_data(k, data)

    def _on_children(self, kids) -> None:
        names = set(kids or [])
        for k in sorted(names - self._watched):
            self._watched.add(k)
            # the data watcher delivers the child's current record
            # synchronously on attach (fake store) or shortly after
            # (real ZK) — dc-join fires from _on_data either way
            self.store.watcher(self.path + "/" + k).on(
                "data", lambda data, _k=k: self._on_data(_k, data))
        changed = False
        for k in sorted(self._watched - names):
            self._watched.discard(k)
            if self.records.pop(k, None) is not None:
                changed = True
                self.leaves += 1
                self._event("dc-leave", dc=k)
        if changed:
            self.last_event_mono = time.monotonic()
            self._notify()

    def _on_data(self, dc: str, data) -> None:
        rec = self._normalize(dc, data)
        if rec is None:
            # garbage record: a DC we can't route to is a DC we don't
            # know — drop any previous state rather than keep routing
            # on stale peers
            if self.records.pop(dc, None) is not None:
                self.last_event_mono = time.monotonic()
                self._notify()
            return
        prev = self.records.get(dc)
        if prev == rec:
            return
        self.records[dc] = rec
        self.last_event_mono = time.monotonic()
        if prev is None:
            self.joins += 1
            self._event("dc-join", dc=dc, zones=",".join(rec["zones"]),
                        peers=len(rec["peers"]))
        self._notify()

    def _normalize(self, dc: str, data) -> Optional[dict]:
        try:
            obj = json.loads(bytes(data).decode("utf-8")) if data else None
        except (ValueError, UnicodeDecodeError):
            obj = None
        if not isinstance(obj, dict):
            self.log.warning("federation: undecodable DC record at %s/%s",
                             self.path, dc)
            return None
        zones = obj.get("zones") or [dc]
        peers = obj.get("peers") or []
        if not isinstance(zones, list) or not isinstance(peers, list):
            self.log.warning("federation: malformed DC record at %s/%s",
                             self.path, dc)
            return None
        return {"name": dc,
                "zones": [str(z).lower() for z in zones],
                "peers": [str(p) for p in peers]}

    def _event(self, kind: str, **fields) -> None:
        if self.recorder is not None:
            try:
                self.recorder.record(kind, **fields)
            except Exception:  # noqa: BLE001 — observability never fatal
                pass

    def _notify(self) -> None:
        for cb in list(self._cbs):
            try:
                cb()
            except Exception:  # noqa: BLE001 — one consumer must not
                self.log.exception("federation: change callback failed")

    # -- the routing view --

    def foreign_zone_map(self) -> Dict[str, List[str]]:
        """zone label -> peer addresses, excluding our own DC — exactly
        the shape the recursion routing table consumes."""
        out: Dict[str, List[str]] = {}
        for dc, rec in self.records.items():
            if dc == self.self_name:
                continue
            for z in rec["zones"]:
                lst = out.setdefault(z, [])
                for p in rec["peers"]:
                    if p not in lst:
                        lst.append(p)
        return out

    def zone_owner(self, zone: str) -> Optional[str]:
        """Owning (foreign) DC name for a zone label, or None."""
        for dc, rec in self.records.items():
            if dc != self.self_name and zone in rec["zones"]:
                return dc
        return None

    def introspect(self) -> dict:
        last = self.last_event_mono
        return {
            "path": self.path,
            "self": self.self_name,
            "dcs": {dc: {"zones": list(rec["zones"]),
                         "peers": list(rec["peers"])}
                    for dc, rec in sorted(self.records.items())},
            "joins": self.joins,
            "leaves": self.leaves,
            "last_event_age_seconds": (
                None if last is None else time.monotonic() - last),
        }
