"""The serving-plane half of multi-DC federation.

Foreign names already route through the recursion layer (qname's DC
label -> that DC's binders, ``lib/recursion.js:287-354``); federation
supplies the routing table from the watched ``/dcs`` registry and adds
the two things the reference never had:

- a **per-query upstream-work budget** (NXNSAttack, arXiv:2005.09107:
  unbounded cross-resolver fan-out is an amplification vector — a
  single PTR query must not be allowed to touch every binder of every
  DC at once), and
- a **foreign-answer cache with stale-serve** (Resolver-Less DNS,
  arXiv:1908.04574: a previously delivered answer beats a timeout):
  every successful forward deposits the validated upstream wire; when
  the owning DC goes dark (transport-level failure, not a negative
  answer), the cached answer is re-served with its TTL clamped, up to a
  staleness cap — past the cap the query is *withheld* with a
  well-formed denial, mirroring the local degradation policy
  (binder_tpu/policy/degrade.py).  A dark DC never turns into a
  client-visible timeout.

Dark vs alive is decided per-forward: any DNS response (even REFUSED or
NXDOMAIN) proves the peer alive and passes through; only transport
failure (timeout, socket death, all breakers open) reaches the stale
path.  Foreign negative answers therefore stay ordinary negative
answers — see ``UpstreamError.got_response``.
"""
from __future__ import annotations

import asyncio
import logging
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from binder_tpu.dns.wire import Message, Rcode, WireError
from binder_tpu.federation.registry import DcRegistry
from binder_tpu.recursion.recursion import ResolverSource

#: federation config defaults (config key ``federation``)
DEFAULTS = {
    "maxStalenessSeconds": 300.0,   # foreign stale-serve cap
    "staleTtlClampSeconds": 30,     # TTL on stale-served answers
    "exhaustedAction": "servfail",  # or "refused": past-cap denial shape
    "upstreamBudget": 8,            # per-query upstream-work ceiling
    "cacheSize": 4096,              # foreign-answer cache entries
}


class _ForeignCache:
    """Bounded LRU of validated upstream answer wire, keyed
    (qname, qtype).  Values are the raw bytes as received — decoding is
    deferred to the rare dark-serve path; the hot path only appends."""

    def __init__(self, max_entries: int) -> None:
        self.max_entries = max(16, int(max_entries))
        self._d: "OrderedDict[Tuple[str, int], Tuple[bytes, float, str]]" \
            = OrderedDict()

    def put(self, key: Tuple[str, int], wire: bytes, dc: str) -> None:
        d = self._d
        if key in d:
            del d[key]
        elif len(d) >= self.max_entries:
            d.popitem(last=False)
        d[key] = (wire, time.monotonic(), dc)

    def get(self, key: Tuple[str, int]
            ) -> Optional[Tuple[bytes, float, str]]:
        ent = self._d.get(key)
        if ent is not None:
            self._d.move_to_end(key)
        return ent

    def __len__(self) -> int:
        return len(self._d)


class _RegistrySource(ResolverSource):
    """Feeds the recursion routing table from the DC registry — the
    whole breaker/hedge/splice machinery is reused unchanged."""

    def __init__(self, federation: "Federation") -> None:
        self._fed = federation

    async def list_resolvers(self, region_name: str) -> List[Dict[str, str]]:
        return [{"datacenter": zone, "ip": peer}
                for zone, peers in
                self._fed.registry.foreign_zone_map().items()
                for peer in peers]


class Federation:
    """One binder cluster's view of the federated namespace."""

    def __init__(self, *, store, dns_domain: str, datacenter_name: str,
                 config: Optional[dict] = None, collector=None,
                 recorder=None, log: Optional[logging.Logger] = None
                 ) -> None:
        cfg = dict(DEFAULTS)
        cfg.update(config or {})
        self.log = log or logging.getLogger("binder.federation")
        self.recorder = recorder
        self.dns_domain = dns_domain.lower()
        self.datacenter_name = datacenter_name
        self.max_staleness = float(cfg["maxStalenessSeconds"])
        self.ttl_clamp = int(cfg["staleTtlClampSeconds"])
        self.exhausted_action = str(cfg["exhaustedAction"]).lower()
        self.upstream_budget = (None if cfg["upstreamBudget"] in
                                (None, 0, "0") else int(cfg["upstreamBudget"]))
        self.cache = _ForeignCache(int(cfg["cacheSize"]))
        self.registry = DcRegistry(
            store, self_name=datacenter_name,
            path=str(cfg.get("dcsPath", "/dcs")),
            static_records=cfg.get("dcs"),
            log=self.log, recorder=recorder)
        self.registry.on_change(self._membership_changed)
        self.recursion = None
        #: dc name -> {"dark", "since", "first_fail", "stale_served"}
        self._health: Dict[str, dict] = {}
        #: most recent failover convergence: first failed forward to a
        #: newly-dark DC -> first stale-served answer for it (seconds)
        self.last_convergence_s: Optional[float] = None
        self.forwards = 0
        self._register_metrics(collector)

    def _register_metrics(self, collector) -> None:
        if collector is None:
            class _Nop:
                def inc(self, by=1.0):
                    pass
            nop = _Nop()
            self._m_forward_family = None
            self.m_forwards_all = nop
            self.m_hits = self.m_stale = self.m_withheld = nop
            self.m_budget = self.m_failovers = nop
            self._m_forward_children = {}
            return
        collector.gauge(
            "binder_federation_dcs",
            "datacenters currently in the /dcs registry"
        ).set_function(lambda: float(len(self.registry.records)))
        collector.gauge(
            "binder_federation_convergence_seconds",
            "latest failover convergence: first failed forward to a "
            "newly-dark DC until its first stale-served answer"
        ).set_function(lambda: float(self.last_convergence_s or 0.0))
        fam = collector.counter(
            "binder_federation_forwards_total",
            "cross-DC forwards dispatched, by destination datacenter")
        self._m_forward_family = fam
        # "(all)" pins the family (and the dc label) from scrape 1
        self.m_forwards_all = fam.labelled({"dc": "(all)"})
        self.m_forwards_all.inc(0)
        self._m_forward_children: Dict[str, object] = {}
        # .labelled() children: the Counter family object itself has no
        # inc(); the no-label child is the one-series-per-process handle
        self.m_hits = collector.counter(
            "binder_federation_foreign_hits_total",
            "dark-DC queries answered from the foreign-answer cache "
            "(stale-served or withheld)").labelled()
        self.m_stale = collector.counter(
            "binder_federation_foreign_stale_served_total",
            "foreign answers served stale (TTL-clamped) for a dark DC"
        ).labelled()
        self.m_withheld = collector.counter(
            "binder_federation_foreign_withheld_total",
            "foreign answers withheld past the staleness cap").labelled()
        self.m_budget = collector.counter(
            "binder_federation_budget_clamped_total",
            "queries whose upstream fan-out hit the per-query budget"
        ).labelled()
        self.m_failovers = collector.counter(
            "binder_federation_failovers_total",
            "DC dark transitions observed by the forwarding plane"
        ).labelled()
        for m in (self.m_hits, self.m_stale, self.m_withheld,
                  self.m_budget, self.m_failovers):
            m.inc(0)

    # -- lifecycle / wiring --

    def start(self) -> None:
        self.registry.start()

    def resolver_source(self) -> ResolverSource:
        return _RegistrySource(self)

    def attach(self, recursion) -> None:
        """Cross-wire with the recursion plane: it consults us on
        forward success/failure, we push membership changes into its
        routing table and set its upstream budget."""
        self.recursion = recursion
        recursion.federation = self
        if self.upstream_budget is not None:
            recursion.upstream_budget = self.upstream_budget

    def _membership_changed(self) -> None:
        rec = self.recursion
        if rec is None:
            return
        # re-pull the routing table NOW — convergence is watch-delivery
        # latency, not the 5-minute discovery poll
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return      # pre-loop setup: wait_ready()'s refresh covers it
        rec._spawn(rec.refresh())

    # -- forward-outcome feed (called by the recursion plane) --

    def _zone_of(self, domain: str) -> Optional[str]:
        if not domain.endswith(self.dns_domain):
            return None
        prefix = domain[:len(domain) - len(self.dns_domain) - 1]
        return prefix[prefix.rfind(".") + 1:]

    def _dc_for(self, domain: str) -> str:
        zone = self._zone_of(domain)
        if zone is None:
            return "(other)"
        return self.registry.zone_owner(zone) or zone

    def note_forward(self, domain: str) -> None:
        """A cross-DC forward is being dispatched."""
        self.forwards += 1
        self.m_forwards_all.inc()
        if self._m_forward_family is not None:
            dc = self._dc_for(domain)
            child = self._m_forward_children.get(dc)
            if child is None:
                if len(self._m_forward_children) < 64:   # label cardinality
                    child = self._m_forward_family.labelled({"dc": dc})
                    self._m_forward_children[dc] = child
                else:
                    child = self.m_forwards_all
            child.inc()

    def note_success(self, domain: str, qtype: int,
                     raw_up: Optional[bytes]) -> None:
        """A forward got a DNS response (any rcode): the DC is alive;
        deposit positive answers in the foreign cache."""
        dc = self._dc_for(domain)
        h = self._health.get(dc)
        if h is not None:
            if h["dark"]:
                h.update(dark=False, since=time.monotonic(),
                         stale_served=False)
                self._event("dc-recovered", dc=dc)
            h["first_fail"] = None
        if (raw_up is not None and len(raw_up) >= 12
                and ((raw_up[6] << 8) | raw_up[7]) > 0
                and (raw_up[3] & 0x0F) == Rcode.NOERROR):
            self.cache.put((domain, qtype), bytes(raw_up), dc)

    def _note_failure(self, domain: str) -> str:
        dc = self._dc_for(domain)
        now = time.monotonic()
        h = self._health.setdefault(
            dc, {"dark": False, "since": now, "first_fail": None,
                 "stale_served": False})
        if h["first_fail"] is None:
            h["first_fail"] = now
        if not h["dark"]:
            h.update(dark=True, since=now, stale_served=False)
            self.m_failovers.inc()
            self._event("dc-dark", dc=dc)
            self.log.warning("federation: datacenter %s is dark "
                             "(transport-level forward failure); foreign "
                             "answers served stale up to %.0fs", dc,
                             self.max_staleness)
        return dc

    def serve_dark(self, query, domain: str) -> bool:
        """A forward failed at transport level (no DNS response at
        all).  Serve the cached foreign answer per the degradation
        policy, or withhold; returns False when there is nothing cached
        — the ordinary REFUSED path then owns the query.  Never leaves
        the client waiting."""
        dc = self._note_failure(domain)
        ent = self.cache.get((domain, query.qtype()))
        if ent is None:
            return False
        self.m_hits.inc()
        wire, stored, _dc = ent
        age = time.monotonic() - stored
        if age <= self.max_staleness:
            try:
                answers = Message.decode(wire).answers
            except WireError:
                return False
            rebuild = (self.recursion._rebuild if self.recursion is not None
                       else lambda _d, _r: None)
            served = False
            for rec in answers:
                rebuilt = rebuild(domain, rec)
                if rebuilt is not None:
                    rebuilt.ttl = min(rebuilt.ttl, self.ttl_clamp)
                    query.add_answer(rebuilt)
                    served = True
            if not served:
                return False
            self.m_stale.inc()
            query.log_ctx["federation"] = "stale"
            h = self._health.get(dc)
            if h is not None and h["dark"] and not h["stale_served"]:
                h["stale_served"] = True
                first = h["first_fail"] or h["since"]
                self.last_convergence_s = time.monotonic() - first
                self._event("federation-failover", dc=dc,
                            convergence_ms=round(
                                self.last_convergence_s * 1000.0, 1))
            query.stamp("foreign-stale")
            query.respond()
            return True
        # past the staleness cap: withheld — a well-formed denial,
        # never a timeout (same posture as the local policy's
        # stale-exhausted state)
        self.m_withheld.inc()
        query.log_ctx["federation"] = "withheld"
        query.set_error(Rcode.REFUSED if self.exhausted_action == "refused"
                        else Rcode.SERVFAIL)
        query.stamp("foreign-withheld")
        query.respond()
        return True

    # -- observability --

    def _event(self, kind: str, **fields) -> None:
        if self.recorder is not None:
            try:
                self.recorder.record(kind, **fields)
            except Exception:  # noqa: BLE001 — observability never fatal
                pass

    def dark_dcs(self) -> List[str]:
        return sorted(dc for dc, h in self._health.items() if h["dark"])

    def introspect(self) -> dict:
        now = time.monotonic()
        health = {}
        for dc, h in sorted(self._health.items()):
            health[dc] = {
                "dark": h["dark"],
                "age_seconds": now - h["since"],
                "stale_served_since_dark": h["stale_served"],
            }
        return {
            "datacenter": self.datacenter_name,
            "registry": self.registry.introspect(),
            "zone_map": self.registry.foreign_zone_map(),
            "health": health,
            "dark": self.dark_dcs(),
            "forwards": self.forwards,
            "foreign_cache": {
                "entries": len(self.cache),
                "max_entries": self.cache.max_entries,
            },
            "policy": {
                "max_staleness_seconds": self.max_staleness,
                "stale_ttl_clamp_seconds": self.ttl_clamp,
                "exhausted_action": self.exhausted_action,
                "upstream_budget": self.upstream_budget,
            },
            "last_convergence_seconds": self.last_convergence_s,
        }
