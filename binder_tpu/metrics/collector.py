"""Prometheus-style metric collectors + text exposition.

Artedi/triton-metrics equivalent (reference ``lib/server.js:31-34,456-469``
and ``main.js:134-152``), built on the stdlib only.  Provides the same
three binder metrics with the same names:

- ``binder_requests_completed``        counter,   labeled by qtype
- ``binder_request_latency_seconds``   histogram, labeled by qtype
- ``binder_response_size_bytes``       histogram, labeled by qtype

plus a ``/metrics`` scrape endpoint served on service-port+1000 (the Triton
convention, reference ``main.js:144-151``).
"""
from __future__ import annotations

import logging
import threading
from bisect import bisect_left
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

# artedi's default buckets are log-linear; these are the standard prometheus
# client defaults, which cover the same DNS-latency range.
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
DEFAULT_SIZE_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096, 8192)
# Per-stage attribution histograms (binder_query_stage_seconds): single
# phases run from a few µs (mirror probe, splice) up to cross-DC RTTs in
# ms, so the grid extends two decades below the request-latency buckets.
DEFAULT_STAGE_BUCKETS = (
    0.000005, 0.00001, 0.000025, 0.00005, 0.0001, 0.00025, 0.0005,
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0)


def _labels_key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


def _fmt_labels(key: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class CounterChild:
    """Pre-resolved label handle — the per-query fast path skips the
    label-dict sort entirely (prometheus-client 'child' pattern)."""

    __slots__ = ("_counter", "_key")

    def __init__(self, counter: "Counter", key: Tuple) -> None:
        self._counter = counter
        self._key = key

    def inc(self, by: float = 1.0) -> None:
        c = self._counter
        with c._lock:
            c._values[self._key] = c._values.get(self._key, 0.0) + by


class Counter:
    def __init__(self, name: str, help: str) -> None:
        self.name = name
        self.help = help
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()

    def labelled(self, labels: Optional[Dict[str, str]] = None) \
            -> CounterChild:
        return CounterChild(self, _labels_key(labels))

    def increment(self, labels: Optional[Dict[str, str]] = None,
                  by: float = 1.0) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._values.get(_labels_key(labels), 0.0)

    def total(self) -> float:
        """Sum over every label set — the per-shard stats reports use
        this for requests-completed without building an exposition."""
        with self._lock:
            return sum(self._values.values())

    def expose(self, static: Tuple[Tuple[str, str], ...] = ()) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} counter"]
        for key, v in sorted(self._values.items()):
            lines.append(f"{self.name}{_fmt_labels(static + key)} {v:g}")
        return "\n".join(lines)


class Gauge:
    """Point-in-time value collector.  Besides ``set()``, a label set can
    be bound to a callable sampled at scrape time (``set_function``) —
    how structural values like mirrored-node counts are exported without
    bookkeeping on the mutation paths (the reference gets the analogous
    zkstream client gauges for free by passing its artedi collector in,
    ``lib/zk.js:26-38``)."""

    def __init__(self, name: str, help: str) -> None:
        self.name = name
        self.help = help
        self._values: Dict[Tuple, float] = {}
        self._functions: Dict[Tuple, object] = {}
        self._lock = threading.Lock()

    def set(self, value: float,
            labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_labels_key(labels)] = value

    def set_function(self, fn, labels: Optional[Dict[str, str]] = None) \
            -> None:
        with self._lock:
            self._functions[_labels_key(labels)] = fn

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        key = _labels_key(labels)
        fn = self._functions.get(key)
        if fn is not None:
            return float(fn())
        return self._values.get(key, 0.0)

    def expose(self, static: Tuple[Tuple[str, str], ...] = ()) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        with self._lock:
            merged = dict(self._values)
            for key, fn in self._functions.items():
                try:
                    merged[key] = float(fn())
                except Exception:  # noqa: BLE001 — one bad sampler must
                    continue       # not take down the whole scrape
        for key, v in sorted(merged.items()):
            lines.append(f"{self.name}{_fmt_labels(static + key)} {v:g}")
        return "\n".join(lines)


class HistogramChild:
    """Pre-resolved label handle.  ``observe`` touches exactly one
    (non-cumulative) bucket cell via bisect instead of incrementing every
    bucket ≥ value; exposition re-accumulates to the cumulative
    prometheus form."""

    __slots__ = ("_hist", "_key", "_cells")

    def __init__(self, hist: "Histogram", key: Tuple) -> None:
        self._hist = hist
        self._key = key
        with hist._lock:
            self._cells = hist._counts.setdefault(
                key, [0] * (len(hist.buckets) + 1))

    def observe(self, value: float) -> None:
        h = self._hist
        with h._lock:
            self._cells[bisect_left(h.buckets, value)] += 1
            h._sums[self._key] = h._sums.get(self._key, 0.0) + value

    def merge(self, cells: Sequence[int], sum_delta: float) -> None:
        """Bulk-add externally accumulated (non-cumulative) bucket cells —
        how natively counted observations (the fast-path drain) fold in at
        scrape time.  ``cells`` must match this histogram's layout:
        len(buckets)+1 with the +Inf cell last."""
        h = self._hist
        with h._lock:
            for i, delta in enumerate(cells):
                if delta:
                    self._cells[i] += delta
            h._sums[self._key] = h._sums.get(self._key, 0.0) + sum_delta


class Histogram:
    # _counts stores per-bucket (NON-cumulative) cells, one extra slot
    # for +Inf; cumulative conversion happens at scrape time
    def __init__(self, name: str, help: str,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS) -> None:
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._lock = threading.Lock()

    def labelled(self, labels: Optional[Dict[str, str]] = None) \
            -> HistogramChild:
        return HistogramChild(self, _labels_key(labels))

    def observe(self, value: float,
                labels: Optional[Dict[str, str]] = None) -> None:
        key = _labels_key(labels)
        with self._lock:
            cells = self._counts.setdefault(
                key, [0] * (len(self.buckets) + 1))
            cells[bisect_left(self.buckets, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value

    def count(self, labels: Optional[Dict[str, str]] = None) -> int:
        return sum(self._counts.get(_labels_key(labels), ()))

    def expose(self, static: Tuple[Tuple[str, str], ...] = ()) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        for key in sorted(self._counts):
            cells = self._counts[key]
            full = static + key
            running = 0
            for i, b in enumerate(self.buckets):
                running += cells[i]
                # no escapes inside f-string expressions (a backslash
                # there is a SyntaxError before Python 3.12)
                le = 'le="%g"' % b
                lines.append(
                    f"{self.name}_bucket{_fmt_labels(full, le)} "
                    f"{running}")
            total = running + cells[len(self.buckets)]
            inf = 'le="+Inf"'
            lines.append(f"{self.name}_bucket"
                         f"{_fmt_labels(full, inf)} {total}")
            lines.append(f"{self.name}_sum{_fmt_labels(full)} "
                         f"{self._sums.get(key, 0.0):g}")
            lines.append(f"{self.name}_count{_fmt_labels(full)} {total}")
        return "\n".join(lines)


class MetricsCollector:
    """Registry of named collectors (artedi createCollector analog)."""

    def __init__(self,
                 static_labels: Optional[Dict[str, str]] = None) -> None:
        self._collectors: Dict[str, object] = {}
        self.static_labels = static_labels or {}
        self._expose_hooks: List = []

    def on_expose(self, fn) -> None:
        """Register a pre-scrape hook (e.g. folding natively accumulated
        fast-path counts into the collectors)."""
        self._expose_hooks.append(fn)

    def counter(self, name: str, help: str = "") -> Counter:
        c = self._collectors.get(name)
        if c is None:
            c = Counter(name, help)
            self._collectors[name] = c
        return c  # type: ignore[return-value]

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS
                  ) -> Histogram:
        h = self._collectors.get(name)
        if h is None:
            h = Histogram(name, help, buckets)
            self._collectors[name] = h
        return h  # type: ignore[return-value]

    def gauge(self, name: str, help: str = "") -> Gauge:
        g = self._collectors.get(name)
        if g is None:
            g = Gauge(name, help)
            self._collectors[name] = g
        return g  # type: ignore[return-value]

    def get(self, name: str):
        return self._collectors.get(name)

    def fold(self) -> None:
        """Run the pre-scrape fold hooks WITHOUT building exposition
        text — how a shard worker keeps its natively counted serves
        current in the 1 Hz stats frames it sends the supervisor."""
        for fn in self._expose_hooks:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a fold bug must not
                logging.getLogger("binder.metrics").exception(
                    "fold hook %r failed", fn)   # stop the stats loop

    def expose(self) -> str:
        for fn in self._expose_hooks:
            try:
                fn()
            except Exception:  # noqa: BLE001 — scrape must not 500 on a
                # fold-in bug, but a silently-failing hook means natively
                # counted queries vanish from dashboards: log it
                logging.getLogger("binder.metrics").exception(
                    "pre-scrape hook %r failed", fn)
        static = _labels_key({k: str(v) for k, v in
                              self.static_labels.items() if v is not None})
        return "\n".join(c.expose(static)
                         for c in self._collectors.values()) + "\n"


class MetricsServer:
    """Threaded HTTP scrape server on service-port+1000
    (triton-metrics analog).  Besides ``/metrics``, serves the
    kang-style introspection snapshot on ``/status`` (and the kang
    alias ``/kang/snapshot``) when ``status_source`` is set to a
    callable returning a JSON-serializable object — one port covers
    both the time-series and the state views."""

    def __init__(self, collector: MetricsCollector, address: str = "0.0.0.0",
                 port: int = 0) -> None:
        self.collector = collector
        # set post-construction (the Introspector needs the running
        # server wired first); consulted per request
        self.status_source = None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, body: bytes, ctype: str,
                       code: int = 200) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                import json as _json
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._reply(outer.collector.expose().encode(),
                                "text/plain; version=0.0.4")
                    return
                if (path in ("/status", "/kang/snapshot")
                        and outer.status_source is not None):
                    try:
                        snap = outer.status_source()
                        body = _json.dumps(snap, default=str,
                                           indent=1).encode()
                    except Exception as e:  # noqa: BLE001 — a snapshot
                        # bug must answer 500, not hang the scraper
                        body = _json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}).encode()
                        self._reply(body, "application/json", 500)
                        return
                    self._reply(body, "application/json")
                    return
                self.send_response(404)
                self.end_headers()

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer((address, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
