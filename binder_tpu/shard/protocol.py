"""Shard mutation-log framing: the supervisor <-> worker wire format.

One UNIX ``socketpair`` per shard carries two ordered streams:

- supervisor -> worker: the **mutation log** — a snapshot of the owner
  mirror (``node`` frames for every mirrored name, bracketed by a
  ``state`` frame and ``snap-end``) followed by an endless delta feed
  (``node`` upserts / ``gone`` removals, emitted from the owner
  MirrorCache's per-name invalidation events) plus periodic session
  ``state`` heartbeats.  Replaying this stream against a fresh
  :class:`~binder_tpu.shard.replica.ReplicaStore` reproduces the
  owner's mirror exactly — which is why a respawned shard catches up
  by simply reading from the top (snapshot + replay on attach).
- worker -> supervisor: one ``hello`` after the serve stack is up
  (pid + bound ports), then 1 Hz ``stats`` frames the supervisor folds
  into the aggregated ``binder_shard_*`` metrics and ``/status``.

Framing is 4-byte big-endian length + UTF-8 JSON.  Node data rides as
the owner mirror's *parsed* JSON (re-serialized), not raw znode bytes:
the mirror is the source of truth in shard mode, so every worker
converges to the owner's view even for znodes whose bytes never parsed.
"""
from __future__ import annotations

import hashlib
import json
from typing import List, Optional

#: protocol version, carried in the state frame so a mixed-version
#: supervisor/worker pair fails loudly instead of misapplying frames
SHARD_PROTO_VERSION = 1

#: env var carrying the worker's inherited socketpair fd
SHARD_FD_ENV = "BINDER_SHARD_FD"

#: hard cap on one frame (a 1M-name snapshot ships as many small
#: frames, never one big one; anything larger is a corrupt stream)
MAX_FRAME = 16 << 20


def encode_frame(obj: dict) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME:
        raise ValueError(f"shard frame over {MAX_FRAME} bytes")
    return len(body).to_bytes(4, "big") + body


def decode_frames(buf: bytearray) -> List[dict]:
    """Consume every complete frame from *buf* (in place); partial
    tails stay buffered for the next read."""
    out: List[dict] = []
    off = 0
    n = len(buf)
    while n - off >= 4:
        ln = int.from_bytes(buf[off:off + 4], "big")
        if ln > MAX_FRAME:
            raise ValueError(f"shard frame length {ln} over cap")
        if n - off - 4 < ln:
            break
        out.append(json.loads(bytes(buf[off + 4:off + 4 + ln])))
        off += 4 + ln
    del buf[:off]
    return out


def node_frame(domain: str, data, tr: Optional[str] = None,
               t0: Optional[float] = None) -> dict:
    """Upsert one mirrored name (data = the mirror's parsed JSON or
    None for a data-less node).  ``tr``/``t0`` optionally carry the
    owner's propagation-trace id and monotonic origin instant
    (CLOCK_MONOTONIC is machine-wide on Linux, so the replica's stage
    timings land on the owner's timeline); older peers ignore them."""
    f = {"op": "node", "d": domain, "data": data}
    if tr is not None:
        f["tr"] = tr
        f["t0"] = t0
    return f


def gone_frame(domain: str, tr: Optional[str] = None,
               t0: Optional[float] = None) -> dict:
    f = {"op": "gone", "d": domain}
    if tr is not None:
        f["tr"] = tr
        f["t0"] = t0
    return f


def path_node_frame(path: str, data) -> dict:
    """Upsert one RAW-PATH node (federation ``/dcs`` fanout, ROADMAP
    3a): unlike ``node`` frames — which are keyed by lookup domain
    under the served zone — these carry subtrees OUTSIDE the zone that
    workers must still track live (DC join/leave).  Applying one at
    the replica fires the same FakeStore watcher events a local store
    mutation would, so the worker's own ``DcRegistry`` sees membership
    changes with zero registry-side changes.  Deliberately NOT part of
    the replica-parity digest: the digest pins zone-data parity, and
    older peers warn-and-ignore the unknown op."""
    return {"op": "pnode", "p": path, "data": data}


def path_gone_frame(path: str) -> dict:
    """Remove one raw-path node (and its subtree) — the ``pnode``
    counterpart for DC leave."""
    return {"op": "pgone", "p": path}


def state_frame(state: str, connected: bool,
                disconnected_s: Optional[float],
                establishments: int) -> dict:
    return {"op": "state", "v": SHARD_PROTO_VERSION, "state": state,
            "connected": connected, "disc_s": disconnected_s,
            "est": establishments}


def snap_end_frame(nodes: int) -> dict:
    return {"op": "snap-end", "nodes": nodes}


def hello_frame(shard: int, pid: int, udp_port: int, tcp_port: int,
                metrics_port: int) -> dict:
    return {"op": "hello", "shard": shard, "pid": pid,
            "udp_port": udp_port, "tcp_port": tcp_port,
            "metrics_port": metrics_port}


def stats_frame(requests: float, gen: int, epoch: int, ready: bool,
                inflight: int, rrl_dropped: int = 0,
                shed: int = 0) -> dict:
    """1 Hz worker report.  ``rrl_dropped``/``shed`` (response-rate-
    limit drops and total admission sheds, both monotonic per worker
    incarnation) fold into ``binder_shard_rrl_dropped`` /
    ``binder_shard_shed`` so a flood's per-shard spread is scrapeable
    from the supervisor; older workers simply omit them (defaults)."""
    return {"op": "stats", "requests": requests, "gen": gen,
            "epoch": epoch, "ready": ready, "inflight": inflight,
            "rrl_dropped": rrl_dropped, "shed": shed}


def delta_digest(prev: str, frame: dict) -> str:
    """Fold one delta frame into the rolling mutation-log digest.

    Both ends of a shard link roll the same function over the same
    ordered ``node``/``gone`` stream, starting from ``"0"`` at
    ``snap-end`` (the stream is ordered, so the reset point aligns
    even when deltas interleave with a snapshot in flight — unhashed
    on both sides).  Only the replicated substance is hashed: op,
    domain, canonicalized data.  Trace fields (``tr``/``t0``) are
    deliberately excluded — they are observability freight, not
    mirrored state, and older peers never see them at all."""
    h = hashlib.sha256()
    h.update(prev.encode("utf-8"))
    h.update(str(frame.get("op")).encode("utf-8"))
    h.update(b"\x00")
    h.update(str(frame.get("d")).encode("utf-8"))
    h.update(b"\x00")
    h.update(json.dumps(frame.get("data"), sort_keys=True,
                        separators=(",", ":")).encode("utf-8"))
    return h.hexdigest()[:16]


def digest_frame(gen: int, digest: str) -> dict:
    """Supervisor -> worker: the owner's rolling digest after the
    delta batch for generation ``gen`` — the replica compares against
    its own roll (cross-shard replica parity, ISSUE 16); older workers
    warn-and-ignore the unknown op."""
    return {"op": "digest", "gen": gen, "dg": digest}


def digest_report_frame(shard: int, gen: int, ok: bool, have: str,
                        want: str) -> dict:
    """Worker -> supervisor: the outcome of a digest comparison
    (mismatches only — the supervisor counts its own emitted frames as
    checks)."""
    return {"op": "digest-report", "shard": shard, "gen": gen,
            "ok": ok, "have": have, "want": want}


def snapshot_order(domains) -> List[str]:
    """Parents before children (fewer labels first): the replica's
    ``mkdirp`` would create missing parents anyway, but applying in
    tree order means every parent's data lands before its children
    fire the parent's children-watch."""
    return sorted(domains, key=lambda d: (d.count("."), d))
