"""ShardSupervisor: one mirror owner fanning out to N serving shards.

The reference's entire scaling story is N identical single-threaded
processes behind a balancer (PAPER.md L1); ZDNS (arXiv:2309.13495)
makes the same shared-nothing argument for DNS throughput.  This is the
rebuild's version of that story with two deliberate twists:

- **Kernel-balanced sockets.**  Every worker binds the SAME UDP+TCP
  port with ``SO_REUSEPORT``; the kernel's 4-tuple hash spreads
  clients across shards with zero balancer hops on the hot path.  A
  dead worker's socket leaves the reuseport group at once, so its
  share re-hashes to the survivors while the supervisor respawns it.
- **One mirror owner.**  Only the supervisor holds the ZK session and
  the store mirror, no matter how many shards serve — N shards never
  multiply the watch load on the ensemble.  Mutations fan out over a
  per-shard UNIX socketpair mutation log (``shard/protocol.py``):
  snapshot + replay on attach, per-name deltas from the owner
  MirrorCache's invalidation events afterwards.  Each worker's
  precompiler re-renders from that same delta feed, so shard answers
  stay byte-identical (modulo ID/rotation) to the single-process path.

The supervisor also owns the operational surface: it respawns crashed
shards (exponential backoff, snapshot catch-up), drains on SIGTERM
(TERM to workers, bounded wait, KILL stragglers — no orphan PIDs), and
aggregates ``/status`` + Prometheus metrics across shards (the
``binder_shard_*`` family, one ``shard`` label per series; each
worker's own metrics endpoint stays reachable for drill-down — its
port is in the supervisor snapshot).

Zero-downtime rolling operations (SIGHUP / ``roll_all``,
docs/operations.md "Rolling upgrade / config reload"): one shard at a
time, spawn the replacement worker, stream it the attach snapshot,
wait for it to converge (hello + replica ready) and join the
``SO_REUSEPORT`` group — at which point the kernel already splits
load across old AND new — then SIGTERM the old incarnation, which
quiesces (stops accepting, serves out in-flight) and exits.  A
replacement that fails to converge aborts the roll with the old
worker still serving; no client ever sees an empty group.  Config
reload rides the same cycle: the config file is re-read once up
front and each replacement spawns with the fresh config.
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
from collections import deque
from typing import Dict, List, Optional

from binder_tpu.introspect.status import Introspector
from binder_tpu.shard import protocol
from binder_tpu.verify.tracer import PropagationTracer

#: a worker whose stats are older than this is reported down
#: (binder_shard_up 0) even if its PID still exists
STALE_REPORT_S = 5.0

#: respawn backoff: 0.25 * 2^consecutive_failures, capped
RESPAWN_BACKOFF_MAX_S = 5.0

#: per-link outbound cap: a worker that stops draining its mutation
#: log this far behind is wedged — kill it and let snapshot catch-up
#: do its job (bounded memory beats an unbounded replay queue)
MAX_LINK_BUFFER = 256 << 20

#: rolling upgrade: a replacement worker must hello AND report a
#: ready replica within this window, else the step aborts with the
#: old worker still serving
ROLL_CONVERGE_S = 30.0
#: bounded graceful-drain window for the outgoing incarnation (it
#: quiesces and exits on SIGTERM; stragglers are KILLed)
ROLL_DRAIN_S = 10.0

SUPERVISOR_SNAPSHOT_VERSION = 1


class ShardLink:
    """Supervisor-side state for one worker incarnation."""

    __slots__ = ("shard", "proc", "sock", "wbuf", "writer_armed",
                 "hello", "stats", "stats_at", "last_requests",
                 "last_rrl_dropped", "last_shed",
                 "spawned_mono", "rbuf", "closed",
                 "snap_queue", "snap_sent", "snap_started",
                 "dg", "skew_pending")

    def __init__(self, shard: int, proc: subprocess.Popen,
                 sock: socket.socket) -> None:
        self.shard = shard
        self.proc = proc
        self.sock = sock
        self.wbuf = bytearray()
        self.rbuf = bytearray()
        self.writer_armed = False
        self.hello: Optional[dict] = None
        self.stats: Optional[dict] = None
        self.stats_at = 0.0
        # last raw requests figure this incarnation reported, for the
        # monotonic fold into binder_shard_requests across respawns
        self.last_requests = 0.0
        # same per-incarnation baselines for the hostile-traffic fold
        # (binder_shard_rrl_dropped / binder_shard_shed)
        self.last_rrl_dropped = 0.0
        self.last_shed = 0.0
        self.spawned_mono = time.monotonic()
        self.closed = False
        # chunked attach-time snapshot state: the walk queue of owner
        # mirror nodes still to frame (None once snap-end was sent),
        # frames sent so far, and the start instant for the stall
        # backstop
        self.snap_queue: Optional[object] = None
        self.snap_sent = 0
        self.snap_started = 0.0
        # replica-parity digest (ISSUE 16): the owner-side rolling
        # digest over this link's post-snapshot delta stream (None
        # until snap-end), and the chaos `skew-replica` counter of
        # deltas to hash-but-suppress (forcing a detectable mismatch)
        self.dg: Optional[str] = None
        self.skew_pending = 0


class ShardSupervisor:
    def __init__(self, *, options: Dict[str, object], store, cache,
                 collector, recorder=None,
                 log: Optional[logging.Logger] = None,
                 name: str = "binder") -> None:
        self.options = options
        self.store = store
        self.cache = cache
        self.collector = collector
        self.recorder = recorder
        self.log = log or logging.getLogger("binder.shard")
        self.name = name
        self.n = max(1, int(options.get("shards") or 1))
        self.host = str(options.get("host", "0.0.0.0"))
        self.port = int(options.get("port", 0))
        # resolved by shard 0's hello when the configured port is 0
        self.udp_port: Optional[int] = self.port or None
        self.tcp_port: Optional[int] = None
        self.links: Dict[int, ShardLink] = {}
        # rolling upgrade state: replacement links catching up while
        # the incumbent still serves (shard -> ShardLink), the roll
        # counters, and the single-roll-at-a-time guard
        self._roll_links: Dict[int, ShardLink] = {}
        self.rolls: Dict[int, int] = {i: 0 for i in range(self.n)}
        self.roll_aborts = 0
        self._rolling_shard: Optional[int] = None
        self._roll_busy = False
        self.respawns: Dict[int, int] = {i: 0 for i in range(self.n)}
        self._consec_fail: Dict[int, int] = {i: 0 for i in range(self.n)}
        self._respawn_at: Dict[int, float] = {}
        self._requests_total: Dict[int, float] = {}
        self._hello_futs: Dict[int, asyncio.Future] = {}
        self._draining = False
        self._tick_task: Optional[asyncio.Task] = None
        self._tmpdir: Optional[str] = None
        self._cfg_path: Optional[str] = None
        self._last_state: Optional[tuple] = None
        self._rng = random.Random()
        self.started_mono = time.monotonic()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # serving-plane verification (ISSUE 16): the owner-side
        # propagation tracer (mutations are stamped here; workers
        # inherit the context from the delta frames) and the
        # supervisor half of the replica-digest invariant accounting
        self.tracer = PropagationTracer(collector=collector, log=self.log)
        cache.tracer = self.tracer
        self.digest_checks = 0
        self.digest_violations = 0
        self._m_digest_checks = collector.counter(
            "binder_verify_checks_total",
            "serving-plane invariant checks evaluated").labelled(
                {"invariant": "replica-digest"})
        self._m_digest_violations = collector.counter(
            "binder_verify_violations_total",
            "serving-plane invariant violations detected").labelled(
                {"invariant": "replica-digest"})
        self._m_digest_checks.inc(0)
        self._m_digest_violations.inc(0)
        self._register_metrics()
        # the owner mirror's per-name invalidation events ARE the
        # mutation log: every tag maps to a node upsert or removal
        cache.on_invalidate(self._on_invalidate)
        # federation membership rides the same log (ROADMAP 3a): the
        # owner watches /dcs exactly like DcRegistry does and fans
        # join/leave through as raw-path frames, so shard workers track
        # membership LIVE instead of bootstrapping from static config
        fed = options.get("federation") or {}
        self._dcs_path = "/" + str(
            fed.get("dcsPath", "/dcs")).strip("/")
        self._dcs_records: Dict[str, object] = {}
        self._dcs_watched: set = set()
        try:
            store.watcher(self._dcs_path).on(
                "children", self._on_dcs_children)
            store.on_session(self._resync_dcs)
        except Exception:
            self.log.debug("store has no watcher surface; "
                           "/dcs fanout off")

    # -- metrics: the binder_shard_* family (docs/observability.md) --

    def _register_metrics(self) -> None:
        c = self.collector
        c.gauge("binder_shards",
                "configured shard (worker process) count"
                ).set_function(lambda: float(self.n))
        self._respawn_children = {}
        self._request_children = {}
        up = c.gauge("binder_shard_up",
                     "1 when the shard process is alive and reporting")
        pid = c.gauge("binder_shard_pid",
                      "PID of the shard's current incarnation")
        gen = c.gauge("binder_shard_generation",
                      "shard-local mirror mutation generation")
        ready = c.gauge("binder_shard_ready",
                        "1 when the shard's replica mirror is ready")
        respawns = c.counter("binder_shard_respawns",
                             "times the supervisor respawned a crashed "
                             "shard")
        requests = c.counter("binder_shard_requests",
                             "requests completed per shard (folded "
                             "monotonically across respawns)")
        rrl_drops = c.counter("binder_shard_rrl_dropped",
                              "response-rate-limit drops per shard "
                              "(folded monotonically across respawns)")
        shed = c.counter("binder_shard_shed",
                         "queries shed by admission control per shard "
                         "(all reasons, folded monotonically across "
                         "respawns)")
        rolls = c.counter("binder_shard_rolls_total",
                          "completed zero-downtime drain-and-replace "
                          "cycles per shard (rolling upgrade / config "
                          "reload)")
        self._m_roll_aborts = c.counter(
            "binder_shard_roll_aborts_total",
            "rolling-upgrade steps aborted because the replacement "
            "failed to converge (the old worker kept serving)"
        ).labelled()
        self._m_roll_aborts.inc(0)
        self._rrl_drop_children = {}
        self._shed_children = {}
        self._roll_children = {}
        for i in range(self.n):
            labels = {"shard": str(i)}
            up.set_function(lambda i=i: self._up(i), labels)
            pid.set_function(lambda i=i: float(self._pid(i) or 0),
                             labels)
            gen.set_function(lambda i=i: self._stat(i, "gen"), labels)
            ready.set_function(lambda i=i: self._stat(i, "ready"),
                               labels)
            rc = respawns.labelled(labels)
            rc.inc(0)
            self._respawn_children[i] = rc
            qc = requests.labelled(labels)
            qc.inc(0)
            self._request_children[i] = qc
            dc = rrl_drops.labelled(labels)
            dc.inc(0)
            self._rrl_drop_children[i] = dc
            sc = shed.labelled(labels)
            sc.inc(0)
            self._shed_children[i] = sc
            rlc = rolls.labelled(labels)
            rlc.inc(0)
            self._roll_children[i] = rlc

    def _up(self, i: int) -> float:
        link = self.links.get(i)
        if link is None or link.proc.poll() is not None:
            return 0.0
        if link.hello is None:
            return 0.0
        if time.monotonic() - link.stats_at > STALE_REPORT_S \
                and link.stats is not None:
            return 0.0
        return 1.0

    def _pid(self, i: int) -> Optional[int]:
        link = self.links.get(i)
        return None if link is None else link.proc.pid

    def _stat(self, i: int, key: str) -> float:
        link = self.links.get(i)
        if link is None or link.stats is None:
            return 0.0
        return float(link.stats.get(key) or 0)

    # -- lifecycle --

    async def start(self) -> None:
        """Spawn shard 0 first (it resolves an ephemeral port draw for
        the whole reuseport group), then the rest concurrently."""
        self._loop = asyncio.get_running_loop()
        self._tmpdir = tempfile.mkdtemp(prefix="binder-shards-")
        self._spawn(0, self.port)
        hello = await self._wait_hello(0)
        self.udp_port = int(hello["udp_port"])
        self.tcp_port = int(hello["tcp_port"])
        for i in range(1, self.n):
            self._spawn(i, self.udp_port)
        if self.n > 1:
            await asyncio.gather(*[self._wait_hello(i)
                                   for i in range(1, self.n)])
        self._tick_task = self._loop.create_task(self._tick_loop())
        self.log.info("all %d shard(s) serving (pids %s)", self.n,
                      ",".join(str(self._pid(i)) for i in
                               range(self.n)))
        # the canonical "service started" lines, printed ONCE the whole
        # group is up — harnesses key on these exact formats, and a
        # worker's own announce would advertise a group still forming
        self.log.info("UDP DNS service started on %s:%d", self.host,
                      self.udp_port)
        self.log.info("TCP DNS service started on %s:%d", self.host,
                      self.tcp_port)

    async def _wait_hello(self, i: int, timeout: float = 30.0,
                          link: Optional[ShardLink] = None) -> dict:
        link = self.links[i] if link is None else link
        if link.hello is not None:
            return link.hello
        fut = self._loop.create_future()
        self._hello_futs[i] = fut
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._hello_futs.pop(i, None)

    def _worker_config(self, port: int) -> str:
        """Write the resolved worker config once per port draw.  The
        store block is STRIPPED — a worker must never open its own
        store session (that is the whole point of the owner) — and so
        are the supervisor-only knobs."""
        if self._cfg_path is not None:
            return self._cfg_path
        opts = {k: v for k, v in self.options.items()
                if k not in ("shards", "chaos", "store",
                             "balancerSocket", "configFile",
                             "shardWorker")}
        opts["port"] = port
        path = os.path.join(self._tmpdir, "worker-config.json")
        with open(path, "w") as f:
            json.dump(opts, f)
        if port:
            self._cfg_path = path
        return path

    def _spawn(self, i: int, port: int) -> None:
        self.links[i] = self._spawn_link(i, port)

    def _spawn_link(self, i: int, port: int,
                    role: str = "serving") -> ShardLink:
        """Create one worker incarnation WITHOUT installing it as the
        shard's serving link — the rolling upgrade spawns replacements
        that catch up next to the incumbent before promotion."""
        parent, child = socket.socketpair(socket.AF_UNIX,
                                          socket.SOCK_STREAM)
        argv = [sys.executable, "-u", "-m", "binder_tpu.main",
                "-f", self._worker_config(port),
                "--shard-worker", str(i)]
        env = dict(os.environ)
        env[protocol.SHARD_FD_ENV] = str(child.fileno())
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        try:
            proc = subprocess.Popen(argv, pass_fds=(child.fileno(),),
                                    env=env)
        finally:
            child.close()
        parent.setblocking(False)
        link = ShardLink(i, proc, parent)
        self._loop.add_reader(parent.fileno(), self._on_worker_readable,
                              link)
        # attach-time snapshot: the worker replays this, then the
        # delta feed continues seamlessly on the same ordered stream
        self._send_snapshot(link)
        self.log.info("shard %d %s spawned (pid %d)", i, role, proc.pid)
        if self.recorder is not None:
            self.recorder.record("shard-spawn", shard=i, pid=proc.pid,
                                 respawns=self.respawns[i], role=role)
        return link

    # -- federation /dcs fanout (ROADMAP 3a) --

    def _resync_dcs(self) -> None:
        """Session (re-)establishment: pull current /dcs state when
        the store reads synchronously (FakeStore family); real
        ZooKeeper re-delivers through the re-registered watches."""
        import inspect
        get_children = getattr(self.store, "get_children", None)
        get_data = getattr(self.store, "get_data", None)
        if (not callable(get_children) or not callable(get_data)
                or inspect.iscoroutinefunction(get_children)):
            return
        kids = get_children(self._dcs_path)
        if kids is None:
            return
        self._on_dcs_children(kids)
        for k in kids:
            data = get_data(self._dcs_path + "/" + k)
            if data is not None:
                self._on_dcs_data(k, data)

    def _on_dcs_children(self, kids) -> None:
        names = set(kids or [])
        for k in sorted(names - self._dcs_watched):
            self._dcs_watched.add(k)
            # the data watcher delivers the child's current record
            # synchronously on attach (fake store) — dc data flows
            # from _on_dcs_data either way
            self.store.watcher(self._dcs_path + "/" + k).on(
                "data", lambda data, _k=k: self._on_dcs_data(_k, data))
        for k in sorted(self._dcs_watched - names):
            self._dcs_watched.discard(k)
            if k in self._dcs_records:
                del self._dcs_records[k]
                self._dcs_fanout(protocol.path_gone_frame(
                    self._dcs_path + "/" + k))

    def _on_dcs_data(self, dc: str, data) -> None:
        try:
            obj = (json.loads(bytes(data).decode("utf-8"))
                   if data else None)
        except (ValueError, UnicodeDecodeError):
            obj = None
        if self._dcs_records.get(dc) == obj and dc in self._dcs_records:
            return
        self._dcs_records[dc] = obj
        self._dcs_fanout(protocol.path_node_frame(
            self._dcs_path + "/" + dc, obj))

    def _dcs_fanout(self, frame: dict) -> None:
        # _send, NOT _send_delta: raw-path frames stay outside the
        # replica-parity digest (it pins zone data only)
        for link in self._fanout_links():
            self._send(link, frame)

    # -- mutation-log fanout --

    def _state_tuple(self) -> tuple:
        st = self.store
        state = getattr(st, "session_state",
                        lambda: "connected" if st.is_connected()
                        else "never-connected")()
        disc = getattr(st, "disconnected_seconds", lambda: None)()
        est = getattr(st, "session_establishments", 0)
        return (state, bool(st.is_connected()), disc, est)

    def _state_frame(self) -> dict:
        state, connected, disc, est = self._state_tuple()
        return protocol.state_frame(state, connected, disc, est)

    #: node frames per snapshot pump pass (one event-loop callback);
    #: bounds the time the supervisor loop spends framing before it
    #: yields back to heartbeats, stats folding, and the other links
    SNAP_CHUNK = 2048
    #: outbound high-water during a snapshot: the pump pauses above
    #: this and resumes from the writability callback, so a large-zone
    #: snapshot streams at the worker's pace instead of materializing
    #: the whole mirror in the link buffer (the old eager build put a
    #: million-name snapshot straight into wbuf — nearly the wedge-kill
    #: cap — while blocking the loop for the entire walk)
    SNAP_HIGH_WATER = 4 << 20
    #: a snapshot making no progress for this long means a wedged
    #: worker; kill for respawn (snapshot catch-up IS the recovery)
    SNAP_STALL_S = 120.0

    def _send_snapshot(self, link: ShardLink) -> None:
        """Start the CHUNKED attach-time snapshot: a state frame now,
        then node frames streamed in bounded pump passes (tree order —
        parents before children — via a breadth-first walk of the owner
        mirror), then snap-end.  Deltas and state heartbeats produced
        while the snapshot streams simply interleave into the same
        ordered stream: node frames are upserts read from live mirror
        state, so replaying them in any interleaving converges the
        worker to the owner's view."""
        self._send(link, self._state_frame())
        # current federation membership first (ROADMAP 3a): the
        # worker's DcRegistry is live from the instant it attaches
        for dc in sorted(self._dcs_records):
            self._send(link, protocol.path_node_frame(
                self._dcs_path + "/" + dc, self._dcs_records[dc]))
        link.snap_queue = deque()
        link.snap_sent = 0
        link.snap_started = time.monotonic()
        root = self.cache.nodes.get(self.cache.domain)
        if root is not None:
            link.snap_queue.append(root)
        self._pump_snapshot(link)

    def _pump_snapshot(self, link: ShardLink) -> None:
        q = link.snap_queue
        if link.closed or q is None:
            return
        nodes = self.cache.nodes
        n = 0
        while q and n < self.SNAP_CHUNK \
                and len(link.wbuf) < self.SNAP_HIGH_WATER:
            node = q.popleft()
            if nodes.get(node.domain) is not node:
                continue                # subtree left the mirror mid-walk
            for kid in node.children:
                q.append(kid)
            link.wbuf.extend(protocol.encode_frame(
                protocol.node_frame(node.domain, node.data)))
            link.snap_sent += 1
            n += 1
        if n:
            link.snap_started = time.monotonic()   # progress
        self._flush(link)
        if link.closed or link.snap_queue is None:
            return                      # flush may have severed the link
        if q:
            if len(link.wbuf) >= self.SNAP_HIGH_WATER:
                return      # paused: _on_worker_writable resumes the pump
            self._loop.call_soon(self._pump_snapshot, link)
            return
        link.snap_queue = None
        # arm the per-link replica-parity digest at the same stream
        # point the replica does (receiving snap-end): deltas that
        # interleaved with the snapshot stayed unhashed on both ends
        link.dg = "0"
        self._send(link, protocol.snap_end_frame(link.snap_sent))

    def _on_invalidate(self, tags) -> None:
        """Owner-mirror invalidation -> delta frames.  Tags are lookup
        domains and PTR qnames; only forward names under the served
        domain map to mirrored nodes (workers rebuild their own
        reverse index from node data)."""
        if not self.links and not self._roll_links:
            return
        domain = self.cache.domain
        suffix = "." + domain
        # propagation trace context: stamped by the owner mirror's
        # bump_gen; the delta frames carry it so the workers' stages
        # report against the owner's t0
        ctx = self.tracer.current
        tr, t0 = ctx if ctx is not None else (None, None)
        frames = []
        for tag in tags:
            if tag != domain and not tag.endswith(suffix):
                continue
            node = self.cache.lookup(tag)
            frames.append(protocol.node_frame(tag, node.data, tr, t0)
                          if node is not None
                          else protocol.gone_frame(tag, tr, t0))
        if not frames:
            return
        gen = self.cache.gen
        for link in self._fanout_links():
            for frame in frames:
                self._send_delta(link, frame)
            # one digest frame per delta batch: the replica compares
            # its roll against the owner's (replica-digest invariant)
            if not link.closed and link.dg is not None:
                self.digest_checks += 1
                self._m_digest_checks.inc()
                self._send(link, protocol.digest_frame(gen, link.dg))
        self.tracer.observe("shard-frame", ctx)

    def _send_delta(self, link: ShardLink, frame: dict) -> None:
        """One mutation-log delta: roll the link's parity digest, then
        send — unless a chaos ``skew-replica`` armed suppression, in
        which case the digest rolls WITHOUT the send (the replica must
        flag the divergence at the next digest frame)."""
        if link.dg is not None:
            link.dg = protocol.delta_digest(link.dg, frame)
            if link.skew_pending > 0:
                link.skew_pending -= 1
                self.log.warning(
                    "shard %d: suppressing one delta frame "
                    "(chaos skew-replica)", link.shard)
                return
        self._send(link, frame)

    def skew_replica(self, shard: int = -1,
                     frames: int = 1) -> Optional[int]:
        """Chaos ``skew-replica``: suppress the next *frames* delta
        frames to one worker while still folding them into the owner's
        digest roll — the replica-digest invariant must catch the
        divergence within one mutation cycle.  ``shard=-1`` picks a
        live digest-armed link at random; returns the skewed shard (or
        None when no link is eligible)."""
        candidates = [lk for lk in self.links.values()
                      if not lk.closed and lk.dg is not None]
        if not candidates:
            return None
        if shard < 0:
            link = self._rng.choice(candidates)
        else:
            link = self.links.get(shard)
            if link is None or link.closed or link.dg is None:
                return None
        link.skew_pending += max(1, int(frames))
        return link.shard

    def _fanout_links(self) -> List[ShardLink]:
        """Every link the mutation log must reach: the serving set
        plus replacements catching up mid-roll (a replacement that
        missed deltas between its snapshot and promotion would serve
        an aging mirror the moment it binds the reuseport group)."""
        links = list(self.links.values())
        if self._roll_links:
            links.extend(self._roll_links.values())
        return links

    def _kill_link(self, link: ShardLink) -> None:
        """Link-scoped wedge recovery: sever the stream and SIGKILL
        THIS incarnation (``kill_shard`` is index-keyed and would hit
        the serving link — wrong answer for a mid-roll replacement)."""
        self._close_link(link)
        if link.proc.poll() is None:
            try:
                link.proc.kill()
            except (ProcessLookupError, OSError):
                pass

    def _send(self, link: ShardLink, frame: dict) -> None:
        if link.closed:
            return
        link.wbuf.extend(protocol.encode_frame(frame))
        if len(link.wbuf) > MAX_LINK_BUFFER:
            # a worker this far behind on its mutation log is wedged;
            # snapshot catch-up on respawn is the bounded recovery
            self.log.error("shard %d: mutation log %d bytes behind; "
                           "killing for respawn", link.shard,
                           len(link.wbuf))
            self._kill_link(link)
            return
        self._flush(link)

    def _flush(self, link: ShardLink) -> None:
        if link.closed or not link.wbuf:
            return
        try:
            sent = link.sock.send(bytes(link.wbuf))
            del link.wbuf[:sent]
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            # worker died mid-write; the tick loop reaps and respawns
            self._close_link(link)
            return
        if link.wbuf and not link.writer_armed:
            link.writer_armed = True
            self._loop.add_writer(link.sock.fileno(),
                                  self._on_worker_writable, link)

    def _on_worker_writable(self, link: ShardLink) -> None:
        try:
            self._loop.remove_writer(link.sock.fileno())
        except (OSError, ValueError):
            pass
        link.writer_armed = False
        self._flush(link)
        # a paused snapshot resumes once the worker drained us below
        # the high-water mark
        if (link.snap_queue is not None and not link.closed
                and len(link.wbuf) < self.SNAP_HIGH_WATER):
            self._pump_snapshot(link)

    # -- worker -> supervisor frames --

    def _on_worker_readable(self, link: ShardLink) -> None:
        try:
            chunk = link.sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._sever(link)
            return
        if not chunk:
            self._sever(link)
            return
        link.rbuf.extend(chunk)
        try:
            frames = protocol.decode_frames(link.rbuf)
        except ValueError:
            self.log.error("shard %d: corrupt worker stream; killing",
                           link.shard)
            self._kill_link(link)
            return
        for frame in frames:
            op = frame.get("op")
            if op == "hello":
                link.hello = frame
                self._consec_fail[link.shard] = 0
                self.log.info(
                    "shard %d serving: pid %d udp %s tcp %s metrics %s",
                    link.shard, frame.get("pid"), frame.get("udp_port"),
                    frame.get("tcp_port"), frame.get("metrics_port"))
                fut = self._hello_futs.get(link.shard)
                if fut is not None and not fut.done():
                    fut.set_result(frame)
            elif op == "stats":
                self._fold_stats(link, frame)
            elif op == "digest-report":
                self._on_digest_report(link, frame)

    def _on_digest_report(self, link: ShardLink, frame: dict) -> None:
        """A replica flagged a mutation-log digest mismatch: count the
        replica-digest violation and keep the evidence (the replica
        already resynced its roll; operators decide whether to recycle
        the shard — see docs/operations.md)."""
        if frame.get("ok"):
            return
        self.digest_violations += 1
        self._m_digest_violations.inc()
        self.log.error(
            "shard %d: replica digest mismatch at gen %s "
            "(have %s want %s)", link.shard, frame.get("gen"),
            frame.get("have"), frame.get("want"))
        if self.recorder is not None:
            self.recorder.record(
                "verify-violation", invariant="replica-digest",
                shard=link.shard, generation=frame.get("gen"),
                have=frame.get("have"), want=frame.get("want"))

    def _fold_stats(self, link: ShardLink, frame: dict) -> None:
        link.stats = frame
        link.stats_at = time.monotonic()
        req = float(frame.get("requests") or 0.0)
        # monotonic fold: a respawned incarnation restarts its counter
        # at 0, so deltas are per-incarnation
        delta = req - link.last_requests
        if delta < 0:
            delta = req
        link.last_requests = req
        if delta > 0:
            self._request_children[link.shard].inc(delta)
            self._requests_total[link.shard] = \
                self._requests_total.get(link.shard, 0.0) + delta
        for key, attr, children in (
                ("rrl_dropped", "last_rrl_dropped",
                 self._rrl_drop_children),
                ("shed", "last_shed", self._shed_children)):
            val = float(frame.get(key) or 0.0)
            d = val - getattr(link, attr)
            if d < 0:
                d = val
            setattr(link, attr, val)
            if d > 0:
                children[link.shard].inc(d)

    def _sever(self, link: ShardLink) -> None:
        """A dead mutation log means a dead shard: a worker that lost
        its feed can only serve an aging mirror, so force the exit the
        tick loop's respawn path expects."""
        self._close_link(link)
        if link.proc.poll() is None:
            try:
                link.proc.terminate()
            except (ProcessLookupError, OSError):
                pass

    def _close_link(self, link: ShardLink) -> None:
        if link.closed:
            return
        link.closed = True
        link.snap_queue = None
        try:
            self._loop.remove_reader(link.sock.fileno())
        except (OSError, ValueError):
            pass
        if link.writer_armed:
            try:
                self._loop.remove_writer(link.sock.fileno())
            except (OSError, ValueError):
                pass
        try:
            link.sock.close()
        except OSError:
            pass

    # -- crash handling / heartbeat tick --

    async def _tick_loop(self) -> None:
        while not self._draining:
            await asyncio.sleep(0.5)
            try:
                self._tick()
            except Exception:
                self.log.exception("shard supervisor tick failed")

    def _tick(self) -> None:
        # session-state heartbeat (edge-triggered + periodic): workers'
        # degradation policies age on the owner's measured clock
        state = self._state_tuple()
        frame = protocol.state_frame(*state)
        for link in self._fanout_links():
            self._send(link, frame)
        self._last_state = state
        if self._draining:
            return
        now = time.monotonic()
        # snapshot stall backstop: a worker that stopped draining its
        # attach snapshot is wedged — kill it and let respawn + a fresh
        # snapshot do its job
        for link in self._fanout_links():
            if (link.snap_queue is not None and not link.closed
                    and now - link.snap_started > self.SNAP_STALL_S):
                self.log.error("shard %d: snapshot stalled %.0fs; "
                               "killing for respawn", link.shard,
                               now - link.snap_started)
                self._kill_link(link)
        for i in range(self.n):
            if i in self._roll_links:
                # the roll cycle owns this shard's lifecycle: the
                # incumbent may exit (drain) or the replacement may
                # die (abort) without the respawn path interfering
                continue
            link = self.links.get(i)
            if link is not None and link.proc.poll() is None:
                continue
            if link is not None:
                # reap + schedule the respawn with backoff
                rc = link.proc.poll()
                self._close_link(link)
                del self.links[i]
                self.respawns[i] += 1
                self._respawn_children[i].inc()
                self._consec_fail[i] += 1
                backoff = min(RESPAWN_BACKOFF_MAX_S,
                              0.25 * (2 ** (self._consec_fail[i] - 1)))
                self._respawn_at[i] = now + backoff
                self.log.warning(
                    "shard %d (pid %d) exited rc=%s; respawning in "
                    "%.2fs (respawn #%d)", i, link.proc.pid, rc,
                    backoff, self.respawns[i])
                if self.recorder is not None:
                    self.recorder.record("shard-exit", shard=i,
                                         pid=link.proc.pid, rc=rc,
                                         respawns=self.respawns[i])
                continue
            if now >= self._respawn_at.get(i, 0.0) \
                    and self.udp_port is not None:
                self._spawn(i, self.udp_port)

    def kill_shard(self, shard: int = -1,
                   sig: int = signal.SIGKILL) -> Optional[int]:
        """Kill one worker (chaos ``shard-kill``, wedged-link
        recovery).  ``shard=-1`` picks a live one at random.  Returns
        the killed PID (None when nothing was killable)."""
        candidates = [lk for lk in self.links.values()
                      if lk.proc.poll() is None]
        if not candidates:
            return None
        if shard < 0:
            link = self._rng.choice(candidates)
        else:
            link = self.links.get(shard)
            if link is None or link.proc.poll() is not None:
                return None
        pid = link.proc.pid
        try:
            link.proc.send_signal(sig)
        except (ProcessLookupError, OSError):
            return None
        self.log.warning("shard %d: sent signal %d to pid %d",
                         link.shard, sig, pid)
        return pid

    # -- zero-downtime rolling operations (SIGHUP / chaos worker-roll) --

    def request_roll(self, reload_config: bool = False,
                     shard: int = -1) -> Optional[asyncio.Task]:
        """Sync entry point (signal handler, chaos driver): schedule a
        roll of one shard (``shard >= 0``) or the whole group.  A roll
        already in progress absorbs the request — two interleaved
        rolls would race promotions for the same shard slot.  Busy is
        marked HERE, synchronously: a double SIGHUP arrives before the
        scheduled coroutine gets its first tick."""
        if self._roll_busy or self._draining or self._loop is None:
            self.log.warning("rolling upgrade already in progress or "
                             "draining; request ignored")
            return None
        self._roll_busy = True
        if shard >= 0:
            return self._loop.create_task(self._roll_one(shard))
        return self._loop.create_task(
            self.roll_all(reload_config=reload_config))

    async def _roll_one(self, shard: int) -> bool:
        self._roll_busy = True
        try:
            return await self.roll_shard(shard)
        finally:
            self._roll_busy = False

    async def roll_all(self, reload_config: bool = False) -> bool:
        """The zero-downtime rolling operation: one shard at a time —
        spawn replacement, snapshot catch-up, reuseport join, drain
        the incumbent — stopping at the FIRST failed step (a bad
        config or build aborts with every remaining shard untouched
        and still serving)."""
        self._roll_busy = True
        try:
            if reload_config:
                self._reload_options()
            for i in range(self.n):
                if self._draining:
                    return False
                if not await self.roll_shard(i):
                    self.log.error(
                        "rolling upgrade stopped at shard %d; %d "
                        "shard(s) still on the previous incarnation",
                        i, self.n - i)
                    return False
            self.log.info("rolling upgrade complete (%d shard(s))",
                          self.n)
            return True
        finally:
            self._roll_busy = False

    def _reload_options(self) -> bool:
        """Config-reload half of SIGHUP: re-read the config file so
        every subsequent spawn — the roll cycle's replacements first —
        sees the fresh config.  The resolved port, host, and shard
        count are pinned: a reload must never re-draw the reuseport
        group out from under connected clients.  A malformed file
        rolls with the previous config (and says so) — the roll's
        process-replacement half still applies code updates."""
        path = self.options.get("configFile")
        if not path:
            # direct-options deployments (tests, embedding) roll the
            # processes with the current config
            self._cfg_path = None
            return False
        try:
            with open(str(path)) as f:
                fresh = json.load(f)
        except (OSError, ValueError) as e:
            self.log.error("config reload from %s failed (%s); "
                           "rolling with the previous config", path, e)
            return False
        fresh["configFile"] = path
        fresh["shards"] = self.n
        fresh["host"] = self.host
        fresh["port"] = self.port
        self.options = fresh
        self._cfg_path = None
        self.log.info("config reloaded from %s", path)
        return True

    async def roll_shard(self, i: int) -> bool:
        """One drain-and-replace step.  The incumbent keeps serving
        until the replacement has (1) replayed the attach snapshot,
        (2) reported hello — its SO_REUSEPORT sockets are bound, the
        kernel is already splitting load across both incarnations —
        and (3) reported a ready replica over the stats feed.  Only
        then does the incumbent get SIGTERM, quiesce (serve out
        in-flight), and exit.  Every phase is a ``rolling-upgrade``
        flight event; failure to converge aborts with the incumbent
        untouched."""
        if self.udp_port is None or i in self._roll_links \
                or not 0 <= i < self.n:
            return False
        old = self.links.get(i)
        old_pid = old.proc.pid if old is not None else None
        self._rolling_shard = i
        if self.recorder is not None:
            self.recorder.record("rolling-upgrade", phase="spawn",
                                 shard=i, old_pid=old_pid)
        repl = self._spawn_link(i, self.udp_port, role="replacement")
        self._roll_links[i] = repl
        try:
            reason = None
            try:
                await self._wait_hello(i, timeout=ROLL_CONVERGE_S,
                                       link=repl)
            except asyncio.TimeoutError:
                reason = f"no hello within {ROLL_CONVERGE_S:.0f}s"
            if reason is None:
                deadline = time.monotonic() + ROLL_CONVERGE_S
                while True:
                    if repl.closed or repl.proc.poll() is not None:
                        reason = "replacement died during catch-up"
                        break
                    stats = repl.stats
                    if stats is not None and stats.get("ready"):
                        break
                    if time.monotonic() >= deadline:
                        reason = ("replica not ready within "
                                  f"{ROLL_CONVERGE_S:.0f}s")
                        break
                    await asyncio.sleep(0.05)
            if reason is not None:
                self.roll_aborts += 1
                self._m_roll_aborts.inc()
                self.log.error("shard %d roll aborted: %s "
                               "(incumbent pid %s keeps serving)",
                               i, reason, old_pid)
                if self.recorder is not None:
                    self.recorder.record("rolling-upgrade",
                                         phase="abort", shard=i,
                                         reason=reason)
                self._kill_link(repl)
                try:
                    repl.proc.wait(timeout=5)
                except Exception:
                    pass
                return False
            if self.recorder is not None:
                self.recorder.record(
                    "rolling-upgrade", phase="promote", shard=i,
                    old_pid=old_pid, new_pid=repl.proc.pid,
                    snapshot_frames=repl.snap_sent)
            self.links[i] = repl
            if old is not None:
                await self._drain_incumbent(old)
            self.rolls[i] += 1
            self._roll_children[i].inc()
            self.log.info("shard %d rolled: pid %s -> %d", i, old_pid,
                          repl.proc.pid)
            if self.recorder is not None:
                self.recorder.record("rolling-upgrade", phase="done",
                                     shard=i, old_pid=old_pid,
                                     new_pid=repl.proc.pid)
            return True
        finally:
            self._roll_links.pop(i, None)
            self._rolling_shard = None

    async def _drain_incumbent(self, link: ShardLink) -> None:
        """SIGTERM the outgoing incarnation and wait bounded: the
        worker quiesces (leaves the reuseport group, serves out its
        in-flight queries) and exits clean; a straggler is KILLed at
        the deadline."""
        proc = link.proc
        if proc.poll() is None:
            try:
                proc.terminate()
            except (ProcessLookupError, OSError):
                pass
        deadline = time.monotonic() + ROLL_DRAIN_S
        while proc.poll() is None and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        if proc.poll() is None:
            self.log.warning("shard %d: outgoing pid %d ignored the "
                             "drain window; killing", link.shard,
                             proc.pid)
            try:
                proc.kill()
            except (ProcessLookupError, OSError):
                pass
        try:
            proc.wait(timeout=5)
        except Exception:
            pass
        self._close_link(link)

    async def drain(self, timeout: float = 10.0) -> None:
        """SIGTERM drain: stop respawning, TERM every worker, wait
        bounded, KILL stragglers, reap everything — no orphan PIDs."""
        self._draining = True
        if self._tick_task is not None:
            self._tick_task.cancel()
            try:
                await self._tick_task
            except asyncio.CancelledError:
                pass
            self._tick_task = None
        procs: List[subprocess.Popen] = []
        # mid-roll replacements are processes too — no orphan PIDs
        for link in self._fanout_links():
            if link.proc.poll() is None:
                try:
                    link.proc.terminate()
                except (ProcessLookupError, OSError):
                    pass
            procs.append(link.proc)
        deadline = time.monotonic() + timeout
        for proc in procs:
            while proc.poll() is None and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            if proc.poll() is None:
                self.log.warning("shard pid %d ignored SIGTERM; "
                                 "killing", proc.pid)
                try:
                    proc.kill()
                except (ProcessLookupError, OSError):
                    pass
            try:
                proc.wait(timeout=5)
            except Exception:
                pass
        # links close only AFTER the workers had their SIGTERM window:
        # closing first would race their graceful drain with the noisy
        # link-down exit path
        for link in self._fanout_links():
            self._close_link(link)
        self.links.clear()
        self._roll_links.clear()
        if self._tmpdir is not None:
            shutil.rmtree(self._tmpdir, ignore_errors=True)
            self._tmpdir = None
        self.log.info("shard supervisor drained (%d worker(s))",
                      len(procs))

    # -- aggregated /status (served by the supervisor metrics port) --

    def snapshot(self) -> dict:
        now = time.monotonic()
        workers = []
        for i in range(self.n):
            link = self.links.get(i)
            hello = link.hello if link is not None else None
            stats = link.stats if link is not None else None
            workers.append({
                "shard": i,
                "pid": self._pid(i),
                "alive": bool(link is not None
                              and link.proc.poll() is None),
                "up": bool(self._up(i)),
                "state": ("serving" if self._up(i) else
                          "starting" if link is not None else
                          "respawning"),
                "udp_port": hello.get("udp_port") if hello else None,
                "tcp_port": hello.get("tcp_port") if hello else None,
                "metrics_port": (hello.get("metrics_port")
                                 if hello else None),
                "respawns": self.respawns[i],
                "rolls": self.rolls[i],
                "requests": self._requests_total.get(i, 0.0),
                "generation": (stats or {}).get("gen", 0),
                "epoch": (stats or {}).get("epoch", 0),
                "ready": bool((stats or {}).get("ready")),
                "inflight": (stats or {}).get("inflight", 0),
                "last_report_age_seconds": (
                    None if link is None or not link.stats_at
                    else now - link.stats_at),
            })
        intro = Introspector(zk_cache=self.cache, store=self.store,
                             recorder=self.recorder, name=self.name)
        return {
            "service": {
                "name": self.name + "-supervisor",
                "pid": os.getpid(),
                "version": SUPERVISOR_SNAPSHOT_VERSION,
                "uptime_seconds": now - self.started_mono,
                "generated_at": time.time(),
            },
            "store": intro._store_section(),
            "mirror": intro._mirror_section(),
            "shards": {
                "count": self.n,
                "up": sum(1 for w in workers if w["up"]),
                "udp_port": self.udp_port,
                "tcp_port": self.tcp_port,
                "respawns_total": sum(self.respawns.values()),
                "rolls_total": sum(self.rolls.values()),
                "roll_aborts": self.roll_aborts,
                "rolling_shard": self._rolling_shard,
                "digest_checks": self.digest_checks,
                "digest_violations": self.digest_violations,
                "workers": workers,
            },
            "flight_recorder": intro._recorder_section(),
        }
