"""ReplicaStore: a shard worker's view of the one owner mirror.

In shard mode exactly ONE process — the supervisor — holds the ZK
session and the store mirror; workers never open a store connection.
Instead each worker runs this :class:`ReplicaStore`: a
:class:`~binder_tpu.store.fake.FakeStore` (so the whole StoreClient
surface — watchers, initial-state-on-attach, session callbacks — works
unchanged) whose tree is mutated ONLY by mutation-log frames read from
the supervisor socketpair.  The worker's own ``MirrorCache`` sits on
top and re-derives everything a single-process binder would — TreeNode
tree, reverse (PTR) map, generation bumps, per-name invalidation tags
feeding the precompiler and the native caches — from the replayed
deltas, so N shards serve byte-identical answers off one watch load.

Lifecycle:

- ``read_snapshot()`` (blocking, before the serve stack exists)
  consumes the attach-time snapshot: a session ``state`` frame, one
  ``node`` frame per mirrored name, ``snap-end``.  A respawned shard
  catches up exactly this way — snapshot + replay IS the recovery
  story.
- ``start(loop)`` switches the fd to non-blocking delta reading;
  every applied frame fires the same watcher events a local store
  mutation would.
- Supervisor session transitions arrive as ``state`` frames (0.5 s
  heartbeat + edge-triggered): the replica mirrors them into its own
  :class:`SessionStateMixin` machine so the worker's degradation
  policy ages/staleness-caps exactly like the owner's would, and a
  session *re-establishment* replays as ``expire_session`` so the
  worker epoch-flushes its caches like every other full-rebuild path.
- EOF on the fd means the supervisor died: the worker must exit (the
  respawned supervisor has no link to it) via ``on_link_down``.
"""
from __future__ import annotations

import json
import logging
import socket
import time
from typing import Callable, Optional

from binder_tpu.shard import protocol
from binder_tpu.store.cache import domain_to_path
from binder_tpu.store.fake import FakeStore
from binder_tpu.store.names import intern_name


class ShardLinkDown(Exception):
    """The supervisor closed the mutation log (or the stream broke)."""


class ReplicaStore(FakeStore):
    def __init__(self, sock: socket.socket, shard: int,
                 recorder=None,
                 log: Optional[logging.Logger] = None) -> None:
        super().__init__(recorder=recorder)
        self.shard = shard
        self.log = log or logging.getLogger("binder.shard.replica")
        self._sock = sock
        self._rbuf = bytearray()
        self._wbuf = bytearray()
        self._loop = None
        self._writer_armed = False
        self.frames_applied = 0
        self.snapshot_nodes = 0
        # supervisor-reported disconnect age + local receipt instant:
        # disconnected_seconds() keeps aging between heartbeats
        self._sup_disc_s: Optional[float] = None
        self._sup_disc_at = 0.0
        self._sup_est = 0
        # fired (once) when the supervisor link drops; the worker has
        # no way back — its owner and mutation feed are gone
        self.on_link_down: Optional[Callable[[], None]] = None
        self._down = False
        # replica-parity verification (ISSUE 16): the rolling delta
        # digest — None until snap-end arms it (digests hash only
        # post-snapshot deltas, on both ends) — plus the hooks the
        # worker's verify layer wires up: `tracer` receives each delta
        # frame's trace context, `on_digest(gen, ok, have, want)` the
        # outcome of each digest comparison
        self._dg: Optional[str] = None
        self.tracer = None
        self.on_digest: Optional[Callable] = None

    @classmethod
    def from_fd(cls, fd: int, shard: int, **kw) -> "ReplicaStore":
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM,
                             fileno=fd)
        return cls(sock, shard, **kw)

    # -- attach-time snapshot (blocking; runs before the event loop) --

    def read_snapshot(self, timeout: float = 30.0) -> int:
        """Apply frames until ``snap-end``; returns the node count.

        ``timeout`` bounds the time WITHOUT PROGRESS, not the total:
        the supervisor streams large-zone snapshots in bounded chunks
        at the link's pace, so a million-name snapshot legitimately
        takes longer than any fixed total deadline — what signals a
        wedged supervisor is the stream going quiet."""
        self._sock.setblocking(True)
        self._sock.settimeout(timeout)
        deadline = time.monotonic() + timeout
        while True:
            frames = self._recv_frames()
            if frames:
                deadline = time.monotonic() + timeout   # progress
            for frame in frames:
                if frame.get("op") == "snap-end":
                    self.snapshot_nodes = int(frame.get("nodes", 0))
                    self._sock.settimeout(None)
                    # arm the rolling delta digest: the supervisor
                    # resets its per-link roll at the same stream point
                    self._dg = "0"
                    return self.snapshot_nodes
                self._apply(frame)
            if time.monotonic() > deadline:
                raise TimeoutError("shard snapshot stalled for "
                                   f"{timeout}s")

    def _recv_frames(self):
        try:
            chunk = self._sock.recv(1 << 16)
        except socket.timeout:
            raise TimeoutError("shard mutation log stalled mid-snapshot")
        if not chunk:
            raise ShardLinkDown("supervisor closed the mutation log")
        self._rbuf.extend(chunk)
        return protocol.decode_frames(self._rbuf)

    # -- steady state: non-blocking delta feed on the event loop --

    def start(self, loop) -> None:
        self._loop = loop
        self._sock.setblocking(False)
        loop.add_reader(self._sock.fileno(), self._on_readable)

    def _on_readable(self) -> None:
        try:
            while True:
                chunk = self._sock.recv(1 << 16)
                if not chunk:
                    self._link_down("EOF from supervisor")
                    return
                self._rbuf.extend(chunk)
                if len(chunk) < (1 << 16):
                    break
        except (BlockingIOError, InterruptedError):
            pass
        except OSError as e:
            self._link_down(f"mutation log read failed: {e}")
            return
        try:
            frames = protocol.decode_frames(self._rbuf)
        except ValueError as e:
            self._link_down(f"corrupt mutation log: {e}")
            return
        for frame in frames:
            try:
                self._apply(frame)
            except Exception:
                # one bad frame must not stop the feed: the mirror
                # self-heals on the next snapshot (respawn) and the
                # failure is loud in the log
                self.log.exception("shard %d: applying frame %r failed",
                                   self.shard, frame.get("op"))

    def _link_down(self, reason: str) -> None:
        if self._down:
            return
        self._down = True
        self.log.error("shard %d: supervisor link down (%s)",
                       self.shard, reason)
        if self._loop is not None:
            try:
                self._loop.remove_reader(self._sock.fileno())
            except (OSError, ValueError):
                pass
        if self.on_link_down is not None:
            self.on_link_down()

    # -- frame application --

    def _apply(self, frame: dict) -> None:
        op = frame.get("op")
        if op in ("node", "gone"):
            if self._dg is not None:
                self._dg = protocol.delta_digest(self._dg, frame)
            tracer = self.tracer
            if tracer is not None and "tr" in frame:
                # stage the owner's trace context: the apply below
                # fires bump_gen on the worker mirror, which consumes
                # it — so the replica-side stages report against the
                # owner's t0
                tracer.inherit(frame.get("tr"), frame.get("t0"))
            if op == "node":
                # intern the frame's domain: delta frames repeat the
                # same hot names endlessly, and the pool makes each ONE
                # object across the protocol, the replica tree, and the
                # mirror
                self._apply_node(intern_name(str(frame["d"])),
                                 frame.get("data"))
            else:
                self.rmr(domain_to_path(str(frame["d"])))
            if tracer is not None:
                tracer.observe("replica-apply")
                tracer.clear()
        elif op == "pnode":
            # raw-path upsert (federation /dcs fanout): applied at the
            # literal path so the worker's DcRegistry watchers fire
            # exactly as they would against a live store.  Outside the
            # replica-parity digest by design (zone data only).
            self._apply_path(str(frame["p"]), frame.get("data"))
        elif op == "pgone":
            self.rmr(str(frame["p"]))
        elif op == "state":
            self._apply_state(frame)
        elif op == "digest":
            self._check_digest(frame)
        else:
            self.log.warning("shard %d: unknown mutation-log op %r",
                             self.shard, op)
            return
        self.frames_applied += 1

    def _check_digest(self, frame: dict) -> None:
        """Compare the owner's rolling digest against ours; report
        mismatches up-channel (replica-digest invariant).  A replica
        that never finished a snapshot (or an older supervisor that
        never sends digests) simply never compares."""
        if self._dg is None:
            return
        want = str(frame.get("dg", ""))
        gen = int(frame.get("gen", 0))
        have = self._dg
        ok = have == want
        if not ok:
            self.log.error(
                "shard %d: replica digest mismatch at gen %d "
                "(have %s want %s)", self.shard, gen, have, want)
            self.send(protocol.digest_report_frame(
                self.shard, gen, False, have, want))
            # resync to the owner's roll: one detected divergence must
            # not cascade into a mismatch per subsequent digest frame
            self._dg = want
        if self.on_digest is not None:
            try:
                self.on_digest(gen, ok, have, want)
            except Exception:  # noqa: BLE001 — observer bug must not
                self.log.exception("on_digest callback failed")

    def _apply_node(self, domain: str, data) -> None:
        self._apply_path(domain_to_path(domain), data)

    def _apply_path(self, path: str, data) -> None:
        raw = b"" if data is None else json.dumps(data).encode("utf-8")
        if self.exists(path):
            self.set_data(path, raw)
        else:
            # mkdirp fires the parent children-watch (creating the
            # worker-mirror TreeNode) and, for non-empty data, the data
            # watch — exactly the event sequence a fresh znode produces
            self.mkdirp(path, raw)

    def _apply_state(self, frame: dict) -> None:
        st = str(frame.get("state", ""))
        est = int(frame.get("est", 0))
        disc = frame.get("disc_s")
        self._sup_disc_s = None if disc is None else float(disc)
        self._sup_disc_at = time.monotonic()
        if st == "connected":
            if self._connected and est != self._sup_est:
                # the OWNER's session cycled while we stayed attached:
                # replay as expiry so the worker's caches epoch-flush
                # like every other full-rebuild path
                self.expire_session()
            elif not self._connected:
                self.start_session()
        elif st in ("degraded", "expired", "closed"):
            if self._connected or self.session_state() != st:
                self._connected = False
                self._session_transition(st, "supervisor " + st)
        self._sup_est = est

    def disconnected_seconds(self):
        """Owner-measured disconnect age (plus the local heartbeat
        gap), so every shard's degradation policy reads the SAME clock
        the supervisor's mirror is actually aging on."""
        if self._session_state == "connected":
            return 0.0
        if self._sup_disc_s is not None:
            return self._sup_disc_s + (time.monotonic()
                                       - self._sup_disc_at)
        return super().disconnected_seconds()

    # -- worker -> supervisor frames --

    def send(self, frame: dict) -> None:
        """Best-effort non-blocking send (hello/stats).  The supervisor
        is a fast local reader; if its end wedges hard enough to fill
        the socketpair, stats frames drop — serving must not block on
        telemetry."""
        if self._down:
            return
        self._wbuf.extend(protocol.encode_frame(frame))
        self._flush()

    def _flush(self) -> None:
        if not self._wbuf:
            return
        try:
            sent = self._sock.send(bytes(self._wbuf))
            del self._wbuf[:sent]
        except (BlockingIOError, InterruptedError):
            pass
        except OSError as e:
            self._link_down(f"mutation log write failed: {e}")
            return
        if self._wbuf and self._loop is not None \
                and not self._writer_armed:
            self._writer_armed = True
            self._loop.add_writer(self._sock.fileno(), self._on_writable)

    def _on_writable(self) -> None:
        self._loop.remove_writer(self._sock.fileno())
        self._writer_armed = False
        self._flush()

    def close(self) -> None:
        super().close()
        if self._loop is not None:
            try:
                self._loop.remove_reader(self._sock.fileno())
            except (OSError, ValueError):
                pass
            if self._writer_armed:
                try:
                    self._loop.remove_writer(self._sock.fileno())
                except (OSError, ValueError):
                    pass
        try:
            self._sock.close()
        except OSError:
            pass
