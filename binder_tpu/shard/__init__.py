"""Multi-shard serving: N worker processes, one mirror owner.

``main.py --shards N`` (config ``shards``) forks N workers, each
running the full serve stack on kernel-balanced ``SO_REUSEPORT``
sockets, while one supervisor holds the single ZK session/mirror and
fans mutations out over per-shard UNIX socketpair mutation logs
(snapshot + replay on attach).  See docs/operations.md "Sharded
serving" and docs/observability.md for the ``binder_shard_*`` family.
"""
from binder_tpu.shard.replica import ReplicaStore, ShardLinkDown  # noqa: F401
from binder_tpu.shard.supervisor import ShardSupervisor  # noqa: F401
