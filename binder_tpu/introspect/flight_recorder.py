"""Bounded in-memory flight recorder for notable runtime events.

The reference binder's postmortem story is mdb against a core file;
this is the living-process equivalent: a fixed-capacity ring of
structured events (session transitions, watch storms, slow queries,
resolver errors, loop stalls, mirror rebuilds) that costs one deque
append per event, is embedded in the introspection snapshot, and is
dumped to disk on SIGUSR2 — so the minutes *leading up to* an incident
survive the incident.

Thread-safe: events are recorded from the event loop, scrape threads
read snapshots, and the SIGUSR2 dump may run from either.
"""
from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from collections import deque
from typing import Dict, List, Optional

#: Event-type catalog (see docs/observability.md).  record() accepts
#: any string — these are the types the stock wiring emits.
EVENT_TYPES = (
    "session-transition",   # store session state machine edge
    "mirror-rebuild",       # full mirror re-sync (session event)
    "watch-storm",          # mutation rate over MirrorCache.STORM_THRESHOLD
    "slow-query",           # query latency over SLOW_QUERY_MS
    "resolver-error",       # query handler raised (engine error path)
    "loop-stall",           # event-loop lag over the watchdog threshold
    "verify-violation",     # serving-plane invariant check failed
    "dump",                 # a SIGUSR2/explicit dump was taken
)

DEFAULT_CAPACITY = 512


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 log: Optional[logging.Logger] = None) -> None:
        self.capacity = capacity
        self.log = log or logging.getLogger("binder.flight")
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.recorded = 0                   # total ever recorded
        self.by_type: Dict[str, int] = {}
        self._dump_path: Optional[str] = None

    def record(self, etype: str, **data) -> None:
        """Append one event.  ``data`` values must be JSON-serializable
        (enforced at dump time with ``default=str``, so a bad value can
        degrade one field, never the recorder)."""
        now = time.monotonic()
        with self._lock:
            self._seq += 1
            self.recorded += 1
            self.by_type[etype] = self.by_type.get(etype, 0) + 1
            self._events.append({
                "seq": self._seq, "type": etype,
                "t_mono": now, "t_wall": time.time(), **data,
            })

    @property
    def dropped(self) -> int:
        """Events pushed out of the ring by newer ones."""
        with self._lock:
            return self.recorded - len(self._events)

    def events(self, last: Optional[int] = None) -> List[dict]:
        """Snapshot of the ring, oldest first (seq strictly ascending);
        ``last`` limits to the most recent N."""
        with self._lock:
            evs = list(self._events)
        if last is not None and last < len(evs):
            evs = evs[len(evs) - last:]
        return evs

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "recorded": self.recorded,
                "dropped": self.recorded - len(self._events),
                "by_type": dict(self.by_type),
            }

    # -- dumping --

    def default_dump_path(self) -> str:
        return self._dump_path or f"/tmp/binder-flight-{os.getpid()}.json"

    def dump(self, path: Optional[str] = None) -> str:
        """Write the whole ring (plus counters) to ``path`` as JSON and
        record a ``dump`` event; returns the path written."""
        path = path or self.default_dump_path()
        payload = {
            "dumped_at": time.time(),
            "pid": os.getpid(),
            **self.stats(),
            "events": self.events(),
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, default=str, indent=1)
            f.write("\n")
        os.replace(tmp, path)       # readers never see a partial dump
        self.record("dump", path=path, events=len(payload["events"]))
        self.log.info("flight recorder dumped %d event(s) to %s",
                      len(payload["events"]), path)
        return path

    def install_sigusr2(self, loop=None,
                        path: Optional[str] = None) -> None:
        """Arm SIGUSR2 → dump().  With an asyncio loop the handler runs
        as a loop callback (safe with the running server); without one,
        a plain signal handler (the dump only touches the lock and a
        file, both safe outside the loop)."""
        if path:
            self._dump_path = path

        def on_sigusr2(*_args) -> None:
            try:
                self.dump()
            except OSError as e:
                self.log.error("flight recorder dump failed: %s", e)

        if loop is not None:
            loop.add_signal_handler(signal.SIGUSR2, on_sigusr2)
        else:
            signal.signal(signal.SIGUSR2, on_sigusr2)
