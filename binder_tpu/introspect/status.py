"""Kang-style status snapshot: the binder's state, externally visible.

The reference ships kang endpoints because its dominant production
failure is *silent*: a binder serving an aging ZK mirror after session
loss, or an event-loop stall, with every individual query looking
fine.  The :class:`Introspector` assembles one consistent JSON snapshot
of the state side — store session state machine, mirror staleness,
answer-cache economics, the in-flight query table (PR 1's trace IDs
and phase stamps), recursion peers, loop-lag watchdog, and the flight
recorder — served over HTTP by the metrics server's ``/status`` route
and pretty-printed by ``bin/bstat``.

Consistency: the snapshot is built ON the event loop (via
``call_soon_threadsafe`` from scrape threads) whenever a loop handle is
known, so it can never observe the mirror mid-mutation; without a loop
(tests, tools) it is built inline against the synchronous fake store.
"""
from __future__ import annotations

import asyncio
import os
import threading
import time
from typing import Optional

from binder_tpu.store.interface import SESSION_STATES

SNAPSHOT_VERSION = 1

#: events embedded in the snapshot (the dump file carries the full ring)
SNAPSHOT_EVENTS = 50


class Introspector:
    def __init__(self, *, server=None, zk_cache=None, store=None,
                 recursion=None, recorder=None, watchdog=None,
                 collector=None, name: str = "binder") -> None:
        self.server = server
        self.zk_cache = zk_cache if zk_cache is not None else (
            server.zk_cache if server is not None else None)
        self.store = store if store is not None else (
            getattr(self.zk_cache, "store", None))
        self.recursion = recursion if recursion is not None else (
            server.resolver.recursion if server is not None else None)
        self.recorder = recorder if recorder is not None else (
            getattr(server, "recorder", None))
        self.watchdog = watchdog
        self.name = name
        self.started_mono = time.monotonic()
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        if collector is not None:
            self._register_metrics(collector)

    def set_loop(self, loop: asyncio.AbstractEventLoop) -> None:
        """Bind the event loop snapshots must be consistent with."""
        self.loop = loop

    def _register_metrics(self, collector) -> None:
        # one-hot state series: the PromQL-friendly encoding (alert on
        # binder_zk_session_state{state="degraded"} == 1)
        g = collector.gauge(
            "binder_zk_session_state",
            "coordination-store session state machine (1 on the "
            "current state's series, 0 elsewhere)")
        for state in SESSION_STATES:
            g.set_function(
                lambda s=state: 1.0 if self._store_state() == s else 0.0,
                {"state": state})
        collector.gauge(
            "binder_inflight_queries",
            "queries currently in flight past the synchronous serve "
            "path (recursion forwards, async handlers)"
        ).set_function(self._inflight_count)

    def _store_state(self) -> str:
        st = self.store
        if st is None:
            return "never-connected"
        getter = getattr(st, "session_state", None)
        if getter is not None:
            return getter()
        return "connected" if st.is_connected() else "never-connected"

    def _inflight_count(self) -> float:
        if self.server is None:
            return 0.0
        return float(len(self.server.engine.inflight))

    # -- snapshot assembly --

    def snapshot(self) -> dict:
        """One consistent snapshot.  From a foreign thread with a live
        loop bound, the build runs as a loop callback (the loop is the
        only mutator of the structures read); inline otherwise."""
        loop = self.loop
        if loop is not None and loop.is_running():
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if running is not loop:
                box: list = []
                done = threading.Event()

                def build() -> None:
                    try:
                        box.append(self._build())
                    except Exception as e:  # noqa: BLE001 — surface it
                        box.append(e)
                    finally:
                        done.set()

                loop.call_soon_threadsafe(build)
                if done.wait(timeout=2.0) and box:
                    if isinstance(box[0], Exception):
                        raise box[0]
                    return box[0]
                # loop wedged: an inline best-effort build is exactly
                # what an operator diagnosing the wedge needs
        return self._build()

    def _build(self) -> dict:
        return {
            "service": {
                "name": self.name,
                "pid": os.getpid(),
                "version": SNAPSHOT_VERSION,
                "uptime_seconds": time.monotonic() - self.started_mono,
                "generated_at": time.time(),
            },
            "store": self._store_section(),
            "mirror": self._mirror_section(),
            "answer_cache": self._cache_section(),
            "tcp": self._tcp_section(),
            "inflight": self._inflight_section(),
            "recursion": self._recursion_section(),
            "federation": self._federation_section(),
            "precompile": self._precompile_section(),
            "verify": self._verify_section(),
            "policy": self._policy_section(),
            "loop": (self.watchdog.snapshot()
                     if self.watchdog is not None else None),
            "flight_recorder": self._recorder_section(),
        }

    def _precompile_section(self) -> Optional[dict]:
        """Mutation-time precompiler state (null when the feature is
        off): queue depth vs its bound is the backlog signal the
        operations runbook keys on."""
        pc = getattr(self.server, "_precompiler", None) \
            if self.server is not None else None
        return None if pc is None else pc.introspect()

    def _verify_section(self) -> Optional[dict]:
        """Serving-plane verification state (null when the feature is
        off): per-invariant check/violation/skip counts, the recent
        violations table, audit progress, and the mutation-to-glass
        propagation stage latencies (docs/observability.md)."""
        vf = getattr(self.server, "_verify", None) \
            if self.server is not None else None
        return None if vf is None else vf.introspect()

    def _store_section(self) -> dict:
        st = self.store
        now = time.monotonic()
        out = {
            "backend": type(st).__name__ if st is not None else None,
            "state": self._store_state(),
            "connected": bool(st.is_connected()) if st is not None
            else False,
            "disconnected_seconds": None,
            "session_establishments": getattr(
                st, "session_establishments", 0),
            "transitions": [],
        }
        getter = getattr(st, "disconnected_seconds", None)
        if getter is not None:
            out["disconnected_seconds"] = getter()
        for tr in getattr(st, "session_transitions", lambda: [])():
            out["transitions"].append({
                "t_wall": tr["t_wall"],
                "age_seconds": now - tr["t_mono"],
                "from": tr["from"], "to": tr["to"],
                "reason": tr["reason"],
            })
        return out

    def _mirror_section(self) -> dict:
        zc = self.zk_cache
        if zc is None:
            return {"ready": False, "domain": None, "generation": 0,
                    "epoch": 0, "nodes": 0, "names": 0,
                    "reverse_entries": 0, "interned_names": 0,
                    "staleness_seconds": None,
                    "last_rebuild_age_seconds": None,
                    "rebuild": {"pending": 0, "chunks": 0,
                                "last_duration_seconds": None}}
        now = time.monotonic()
        rebuild = getattr(zc, "last_rebuild_mono", None)
        staleness = getattr(zc, "staleness_seconds", lambda: None)()
        pool = getattr(zc, "pool", None)
        return {
            "ready": zc.is_ready(),
            "domain": zc.domain,
            "generation": zc.gen,
            "epoch": zc.epoch,
            # zone scale (ISSUE 7): every bench/status reading carries
            # the size it was measured at ("nodes" kept as the
            # historical alias of the name count)
            "nodes": len(zc.nodes),
            "names": len(zc.nodes),
            "reverse_entries": len(zc.rev_lookup),
            "interned_names": len(pool) if pool is not None else 0,
            "staleness_seconds": staleness,
            "last_rebuild_age_seconds": (
                None if rebuild is None else now - rebuild),
            # chunked session-rebuild state (pending>0 == a re-mirror
            # is streaming underneath live serving right now)
            "rebuild": getattr(zc, "rebuild_info", lambda: {
                "pending": 0, "chunks": 0,
                "last_duration_seconds": None})(),
        }

    def _cache_section(self) -> dict:
        if self.server is None:
            return {"size": 0, "entries": 0, "hits": 0, "misses": 0,
                    "hit_ratio": 0.0, "invalidations": 0,
                    "expiry_ms": 0.0, "neg_hits": 0,
                    "compiled_entries": 0, "compiled_serves": 0,
                    "compiled_installs": 0}
        return self.server.answer_cache.stats()

    def _tcp_section(self) -> dict:
        """Stream-lane state (dns/stream.py): live connection table
        plus accept/promotion/coalesce/drop counters — the "why is TCP
        slow / shedding" section the runbook keys on
        (docs/operations.md)."""
        if self.server is not None:
            return self.server.engine.tcp_introspect()
        return {"open_conns": 0, "max_conns": 0,
                "idle_timeout_seconds": 0.0, "max_write_buffer": 0,
                "cap_refusals": 0, "accepts": 0, "fast_serves": 0,
                "promotions": 0, "oneshot_closes": 0,
                "idle_timeouts": 0, "slow_reader_drops": 0,
                "coalesced_writes": 0, "coalesced_frames": 0,
                "half_closes": 0, "rst_drops": 0}

    def _inflight_section(self) -> dict:
        queries = []
        if self.server is not None:
            for q in list(self.server.engine.inflight.values()):
                queries.append({
                    "trace": q.trace_id,
                    "name": q.name(),
                    "type": q.qtype_name(),
                    "client": q.src[0],
                    "protocol": q.protocol,
                    "age_ms": q.latency_ms(),
                    "phase": q.last_phase(),
                    "phases": dict(q.times),
                })
        return {"count": len(queries), "queries": queries}

    def _recursion_section(self) -> Optional[dict]:
        rec = self.recursion
        return None if rec is None else rec.introspect()

    def _federation_section(self) -> Optional[dict]:
        """Multi-DC federation state (null when this binder is not
        federated): DC registry membership, per-peer health, the
        foreign-answer cache, and failover convergence — the "which
        datacenter owns this name and is it alive" summary the
        operations runbook keys on (docs/federation.md)."""
        fed = getattr(self.server, "federation", None) \
            if self.server is not None else None
        return None if fed is None else fed.introspect()

    def _policy_section(self) -> Optional[dict]:
        """Degradation policy engine state (null when the whole layer
        is off): the stale-serve state machine, overload admission
        counters, and the recursion breakers' worst state — the
        "is binder degraded, and what is it doing about it" summary
        the runbook keys on (docs/degradation.md)."""
        srv = self.server
        pol = getattr(srv, "_policy", None) if srv is not None else None
        adm = getattr(srv, "_admission", None) if srv is not None else None
        rrl = getattr(srv, "_rrl", None) if srv is not None else None
        brk = (getattr(self.recursion, "breakers", None)
               if self.recursion is not None else None)
        if pol is None and adm is None and rrl is None and brk is None:
            return None
        return {
            "degradation": None if pol is None else pol.introspect(),
            "admission": None if adm is None else adm.introspect(
                srv.engine if srv is not None else None),
            "rrl": None if rrl is None else rrl.introspect(),
            "breakers_open": 0 if brk is None else brk.open_count(),
        }

    def _recorder_section(self) -> Optional[dict]:
        if self.recorder is None:
            return None
        out = self.recorder.stats()
        out["events"] = self.recorder.events(last=SNAPSHOT_EVENTS)
        return out
