"""Fold the balancer's stats-socket counters into the Prometheus scrape.

PR 1 put per-stage cycle attribution (frame-parse / cache-probe /
backend-write / reply-relay) on the balancer's stats socket, readable
by ``bin/balstat`` — but dashboards scrape the *backend's* ``/metrics``
endpoint.  This pre-expose hook reads the stats socket at scrape time
and re-exports the stage counters, so ONE scrape covers the C and
Python layers of the deployment unit.

Counter semantics: the balancer reports absolute totals since its own
start.  The fold takes deltas against the last-seen totals (baseline
reset when totals regress, i.e. the balancer restarted), so the
Prometheus series stays monotonic across balancer restarts — the same
discipline as BinderServer's fast-path fold.
"""
from __future__ import annotations

import json
import logging
import socket
import threading
from typing import Optional


class BalancerStatsFold:
    def __init__(self, collector, stats_path: str,
                 timeout: float = 0.5,
                 log: Optional[logging.Logger] = None) -> None:
        self.stats_path = stats_path
        self.timeout = timeout
        self.log = log or logging.getLogger("binder.metrics")
        self._lock = threading.Lock()
        self._last: dict = {}            # stage -> {"cycles", "ops"}
        self._cycles = collector.counter(
            "binder_balancer_stage_cycles",
            "balancer per-stage exclusive TSC cycles (folded from the "
            "stats socket; divide by binder_balancer_cycles_per_us)")
        self._ops = collector.counter(
            "binder_balancer_stage_ops",
            "balancer per-stage timed-region count")
        self._cycles_per_us = collector.gauge(
            "binder_balancer_cycles_per_us",
            "balancer lifetime-calibrated TSC rate")
        self._up = collector.gauge(
            "binder_balancer_up",
            "1 when the balancer stats socket answered the last scrape")
        self._children: dict = {}        # stage -> (cycles, ops) handles
        collector.on_expose(self.fold)

    def read_stats(self) -> dict:
        """One stats-socket round trip (the balancer writes the whole
        JSON document and closes)."""
        c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        c.settimeout(self.timeout)
        try:
            c.connect(self.stats_path)
            buf = b""
            while True:
                chunk = c.recv(65536)
                if not chunk:
                    break
                buf += chunk
        finally:
            c.close()
        return json.loads(buf)

    def _handles(self, stage: str):
        h = self._children.get(stage)
        if h is None:
            labels = {"stage": stage}
            h = (self._cycles.labelled(labels), self._ops.labelled(labels))
            self._children[stage] = h
        return h

    def fold(self) -> None:
        # scrapes run on ThreadingHTTPServer threads: serialize, or two
        # concurrent scrapes double-count the delta
        with self._lock:
            try:
                stats = self.read_stats()
            except (OSError, ValueError):
                # no balancer (not running / not configured on this
                # box) is a normal state, not a scrape error
                self._up.set(0.0)
                return
            self._up.set(1.0)
            self._cycles_per_us.set(float(stats.get("cycles_per_us", 0.0)))
            for stage, cell in (stats.get("stage_cycles") or {}).items():
                if not isinstance(cell, dict):
                    continue
                cyc = int(cell.get("cycles", 0))
                ops = int(cell.get("ops", 0))
                last = self._last.get(stage, {"cycles": 0, "ops": 0})
                if cyc < last["cycles"] or ops < last["ops"]:
                    last = {"cycles": 0, "ops": 0}   # balancer restarted
                ch_cyc, ch_ops = self._handles(stage)
                if cyc > last["cycles"]:
                    ch_cyc.inc(cyc - last["cycles"])
                if ops > last["ops"]:
                    ch_ops.inc(ops - last["ops"])
                self._last[stage] = {"cycles": cyc, "ops": ops}
