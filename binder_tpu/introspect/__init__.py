"""Live introspection & health layer (the kang/mdb analog).

Four pieces (docs/observability.md "State introspection"):

- :class:`~binder_tpu.introspect.status.Introspector` — consistent
  JSON state snapshot served on the metrics server's ``/status`` route
  and pretty-printed by ``bin/bstat``;
- :class:`~binder_tpu.introspect.flight_recorder.FlightRecorder` —
  bounded event ring (session transitions, watch storms, slow queries,
  resolver errors, loop stalls) dumped to disk on SIGUSR2;
- :class:`~binder_tpu.introspect.watchdog.LoopLagWatchdog` — samples
  event-loop scheduling lag into ``binder_loop_lag_seconds`` and fires
  ``loop-stall`` events;
- :class:`~binder_tpu.introspect.balancer_fold.BalancerStatsFold` —
  folds the balancer's stats-socket stage counters into the Prometheus
  scrape so one scrape covers the C and Python layers.
"""
from binder_tpu.introspect.balancer_fold import BalancerStatsFold
from binder_tpu.introspect.flight_recorder import FlightRecorder
from binder_tpu.introspect.status import Introspector
from binder_tpu.introspect.watchdog import LoopLagWatchdog

__all__ = ["BalancerStatsFold", "FlightRecorder", "Introspector",
           "LoopLagWatchdog"]
