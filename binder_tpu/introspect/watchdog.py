"""Event-loop-lag watchdog.

The binder's whole serve path lives on one asyncio loop; anything that
blocks it (a synchronous log sink, a GC pause, a runaway zone refill)
stalls *every* query at once while no individual query looks wrong.
The watchdog samples a monotonic timer on the loop itself: it asks to
wake after ``interval`` seconds and measures how late the wakeup
actually ran.  That lateness IS the scheduling delay every other
callback experienced in the same window.

Samples land in the ``binder_loop_lag_seconds`` histogram; a sample
over ``stall_threshold`` also fires a ``loop-stall`` flight-recorder
event carrying the measured lag.
"""
from __future__ import annotations

import asyncio
import time
from typing import Optional

#: Lag grid: the loop's normal jitter is sub-millisecond; anything in
#: the right half of this grid is a serving-visible stall.
DEFAULT_LAG_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0)

METRIC_LOOP_LAG = "binder_loop_lag_seconds"


class LoopLagWatchdog:
    def __init__(self, collector=None, recorder=None,
                 interval: float = 0.1,
                 stall_threshold: float = 0.25) -> None:
        self.interval = interval
        self.stall_threshold = stall_threshold
        self.recorder = recorder
        self.samples = 0
        self.stalls = 0
        self.last_lag = 0.0
        self.max_lag = 0.0
        self.last_sample_mono: Optional[float] = None
        self._task: Optional[asyncio.Task] = None
        self._hist_child = None
        if collector is not None:
            self._hist_child = collector.histogram(
                METRIC_LOOP_LAG,
                "event-loop scheduling lag sampled by the watchdog "
                "(how late a timer callback ran)",
                buckets=DEFAULT_LAG_BUCKETS).labelled()
            collector.gauge(
                "binder_loop_lag_max_seconds",
                "largest event-loop lag observed since start"
            ).set_function(lambda: self.max_lag)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        while True:
            before = time.monotonic()
            await asyncio.sleep(self.interval)
            now = time.monotonic()
            self._observe(max(0.0, now - before - self.interval), now)

    def _observe(self, lag: float, now: float) -> None:
        """Record one lag sample (separated from the loop for tests)."""
        self.samples += 1
        self.last_lag = lag
        self.last_sample_mono = now
        if lag > self.max_lag:
            self.max_lag = lag
        if self._hist_child is not None:
            self._hist_child.observe(lag)
        if lag >= self.stall_threshold and self.recorder is not None:
            self.stalls += 1
            self.recorder.record("loop-stall", lag_s=round(lag, 6),
                                 threshold_s=self.stall_threshold)

    def snapshot(self) -> dict:
        return {
            "interval_seconds": self.interval,
            "stall_threshold_seconds": self.stall_threshold,
            "samples": self.samples,
            "stalls": self.stalls,
            "last_lag_seconds": self.last_lag,
            "max_lag_seconds": self.max_lag,
        }
