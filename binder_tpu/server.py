"""The binder server: transport engine + resolution + observability.

Port of the reference's ``createServer`` wiring (``lib/server.js:435-660``):
attaches the resolution engine to the transport engine's ``query`` hook,
and metrics + structured query logging to the ``after`` hook.  ``start()``
brings up UDP + TCP listeners and, when configured, the balancer UNIX
socket (``lib/server.js:609-653``).
"""
from __future__ import annotations

import logging
import threading
from typing import Optional

try:  # native fast path (built by `make -C native`); optional
    from binder_tpu import _binderfastio as _fastio
except ImportError:
    _fastio = None

from binder_tpu.dns.query import QueryCtx
from binder_tpu.dns.server import DnsServer
from binder_tpu.dns.wire import (
    ARecord,
    OPTRecord,
    Rcode,
    SRVRecord,
    Type,
)
from binder_tpu.metrics.collector import (
    DEFAULT_SIZE_BUCKETS,
    MetricsCollector,
)
from binder_tpu.resolver.answer_cache import AnswerCache
from binder_tpu.resolver.engine import Resolver
from binder_tpu.utils.jsonlog import log_event
from binder_tpu.utils.probes import ProbeProvider

METRIC_REQUEST_COUNTER = "binder_requests_completed"
METRIC_LATENCY_HISTOGRAM = "binder_request_latency_seconds"
METRIC_SIZE_HISTOGRAM = "binder_response_size_bytes"

SLOW_QUERY_MS = 1000.0  # log at warn above this (lib/server.js:511-514)

# byte values a name label may contain for the native fast path; names
# outside this set are still served, just never through the C cache
# (keep in lockstep with fp_name_ok in native/fastio/fastpath.c)
_FP_NAME_OK = frozenset(
    b"abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_")


def strip_suffix(suffix: str, s: str) -> str:
    """Log redaction of the (long, constant) DNS domain
    (lib/server.js:60-65)."""
    if s.endswith(suffix):
        return s[:len(s) - len(suffix)] + "..."
    return s


class BinderServer:
    def __init__(self, *, zk_cache, dns_domain: str,
                 datacenter_name: str = "",
                 recursion=None,
                 log: Optional[logging.Logger] = None,
                 collector: Optional[MetricsCollector] = None,
                 name: str = "binder",
                 host: str = "127.0.0.1", port: int = 53,
                 balancer_socket: Optional[str] = None,
                 query_log: bool = True,
                 cache_size: int = 10000,
                 cache_expiry_ms: int = 60000,
                 tcp_idle_timeout: Optional[float] = None,
                 max_tcp_conns: Optional[int] = None,
                 max_tcp_write_buffer: Optional[int] = None,
                 probes: Optional[ProbeProvider] = None) -> None:
        self.log = log or logging.getLogger("binder.server")
        self.host = host
        self.port = port
        self.dns_domain = dns_domain
        self.balancer_socket = balancer_socket
        self.collector = collector or MetricsCollector()
        # per-query logging can be disabled for high-qps deployments;
        # slow queries (>1s) are logged regardless
        self.query_log = query_log
        # encoded-answer cache (the reference's -s/-a flags, main.js:34-38)
        self.zk_cache = zk_cache
        self.answer_cache = AnswerCache(size=cache_size,
                                        expiry_ms=cache_expiry_ms)
        self.cache_hit_counter = self.collector.counter(
            "binder_answer_cache_hits", "encoded-answer cache hits")
        self._cache_hit_child = self.cache_hit_counter.labelled()

        self.request_counter = self.collector.counter(
            METRIC_REQUEST_COUNTER, "count of Binder requests completed")
        self.latency_histogram = self.collector.histogram(
            METRIC_LATENCY_HISTOGRAM,
            "total time to process Binder requests")
        self.size_histogram = self.collector.histogram(
            METRIC_SIZE_HISTOGRAM, "size in bytes of Binder responses",
            buckets=DEFAULT_SIZE_BUCKETS)
        # per-qtype pre-resolved metric handles (label-sort once, not
        # per query); key is the numeric qtype
        self._metric_children: dict = {}

        # USDT analog: provider 'binder', probes op-req-start/op-req-done
        # fired with the query context (lib/server.js:24-29,472-474,516-518)
        self.probes = probes or ProbeProvider("binder")
        self.p_req_start = self.probes.probe("op-req-start")
        self.p_req_done = self.probes.probe("op-req-done")

        self.resolver = Resolver(zk_cache, dns_domain=dns_domain,
                                 datacenter_name=datacenter_name,
                                 recursion=recursion, log=self.log)
        self.engine = DnsServer(log=self.log, name=name,
                                tcp_idle_timeout=tcp_idle_timeout,
                                max_tcp_conns=max_tcp_conns,
                                max_tcp_write_buffer=max_tcp_write_buffer)
        self.engine.on_query = self._on_query
        self.engine.on_after = self._on_after
        # the engine's cap-refusal log line is rate-limited, so the
        # counter is the only complete record — surface it in the scrape
        self._cap_refusal_child = self.collector.counter(
            "binder_tcp_cap_refusals",
            "TCP connections refused at the connection cap").labelled()
        self._cap_folded = 0
        self.collector.on_expose(self._fold_engine_counters)

        # Native fast path: answer-cache hits served inside the C UDP
        # drain (native/fastio/fastpath.c).  Python remains the source of
        # truth — completed answer-cache entries are pushed down in
        # _on_query, and the C-side counters fold into the same
        # Prometheus collectors at scrape time (_fold_fastpath_metrics).
        # Balancer answer-cache support: report our mirror generation
        # over balancer links so the balancer can cache responses with
        # correct invalidation (docs/balancer-protocol.md control frames)
        self.engine.gen_source = lambda: self.zk_cache.gen
        if hasattr(zk_cache, "on_mutation"):
            zk_cache.on_mutation(self.engine.notify_mutation)

        self._fastpath = None
        self._fp_folded: dict = {}
        self._fp_fold_lock = threading.Lock()
        if (_fastio is not None and cache_size > 0
                and hasattr(_fastio, "fastpath_new")):
            self._fastpath = _fastio.fastpath_new(
                cache_size, cache_expiry_ms,
                [float(b) for b in self.latency_histogram.buckets],
                [float(b) for b in self.size_histogram.buckets])
            self.engine.fastpath = self._fastpath
            self.engine.fastpath_gen = lambda: self.zk_cache.gen
            self.engine.fastpath_gate = self._fastpath_active
            self.collector.on_expose(self._fold_fastpath_metrics)

        # actual bound ports (for tests / ephemeral binds)
        self.udp_port: Optional[int] = None
        self.tcp_port: Optional[int] = None

    # -- query hook (lib/server.js:471-507); sync, may return an awaitable
    # for the recursion path (see DnsServer._dispatch) --

    def _on_query(self, query: QueryCtx):
        if self.p_req_start.enabled:   # skip closure alloc when off
            self.p_req_start.fire(lambda: {
                "id": query.request.id, "name": query.name(),
                "type": query.qtype_name(), "client": query.src[0],
                "protocol": query.protocol,
            })
        # Answer-cache fast path.  The key is built from the decoded
        # fields the response actually depends on — transport semantics
        # (truncation), RD (drives the recursion-vs-REFUSED split on
        # misses), question, EDNS presence and payload ceiling — NOT the
        # raw wire: wire bytes vary with per-packet EDNS options (DNS
        # cookies, padding) and ignored padding sections, which would
        # mint one key per packet and evict the real entries.
        key = None
        req = query.request
        if len(req.questions) == 1 and req.opcode == 0:
            q0 = req.questions[0]
            key = (query.udp_semantics, req.rd, q0.qtype, q0.qclass,
                   q0.name, req.edns is not None, req.max_udp_payload())
            cached = self.answer_cache.get(key, self.zk_cache.gen)
            if cached is not None:
                wire, ans, add = cached
                self._cache_hit_child.inc()
                query.response.rcode = wire[3] & 0x0F  # for metrics/logs
                query.log_ctx["cached"] = True
                query.cached_summary = (ans, add)
                query.respond_raw(wire)
                return None

        pending = self.resolver.handle(query)

        if (pending is None and key is not None and query.responded
                and query.wire is not None
                and query.rcode() != Rcode.SERVFAIL):
            ans = [self._summarize(r) for r in query.response.answers]
            add = [self._summarize(r) for r in query.response.additionals
                   if not isinstance(r, OPTRecord)]
            # reused by _on_after for this query's own log line too —
            # summaries are built exactly once per resolve
            query.cached_summary = (ans, add)
            gen = self.zk_cache.gen
            completed = self.answer_cache.put(
                key, gen, (query.wire, ans, add),
                rotatable=len(query.response.answers) > 1)
            # push only while the C path can actually drain — with the
            # gate closed (query_log on / probes attached) the native
            # cache would just accumulate dead wires; after a runtime
            # toggle it repopulates from misses within one expiry window
            if (completed and self._fastpath is not None
                    and query.udp_semantics and self._fastpath_active()):
                self._fastpath_push(key, gen, query)
        return pending

    def _fastpath_push(self, key, gen: int, query: QueryCtx) -> None:
        """Hand a just-completed answer-cache entry to the native fast
        path.  The C key is built from the request's raw qname bytes so
        both key builders see identical input; names outside the
        hostname charset (which Python decodes with replacement) are
        skipped — they keep being served by the Python path."""
        ckey = self._fastpath_key(query)
        if ckey is None:
            return
        variants = self.answer_cache.variants(key, gen)
        if not variants:
            return
        wires = [v[0] for v in variants]
        ttl_ms = self.answer_cache.remaining_ttl_ms(key, gen)
        try:
            _fastio.fastpath_put(self._fastpath, ckey, query.qtype(),
                                 gen, wires,
                                 -1 if ttl_ms is None else int(ttl_ms))
        except (TypeError, ValueError, MemoryError) as e:
            self.log.debug("fastpath push skipped: %s", e)

    @staticmethod
    def _fastpath_key(query: QueryCtx) -> Optional[bytes]:
        # layout must match fp_build_key in native/fastio/fastpath.c:
        # [flags rd|edns<<1][payload BE16][qtype BE16][qclass BE16][qname]
        raw = query.raw
        req = query.request
        if raw is None or len(raw) < 17:
            return None
        off = 12
        try:
            while True:
                label_len = raw[off]
                if label_len == 0:
                    off += 1
                    break
                if label_len & 0xC0:
                    return None   # compressed question name: C punts too
                label = raw[off + 1:off + 1 + label_len]
                if (len(label) != label_len
                        or not _FP_NAME_OK.issuperset(label)):
                    return None
                off += 1 + label_len
                if off - 12 > 255:
                    return None
        except IndexError:
            return None
        qname = raw[12:off].lower()
        q0 = req.questions[0]
        flags = (1 if req.rd else 0) | (2 if req.edns is not None else 0)
        return (bytes([flags]) + req.max_udp_payload().to_bytes(2, "big")
                + q0.qtype.to_bytes(2, "big")
                + q0.qclass.to_bytes(2, "big") + qname)

    def _fold_engine_counters(self) -> None:
        # scrapes run on ThreadingHTTPServer threads: fold under the
        # shared lock or two concurrent scrapes double-count the delta
        with self._fp_fold_lock:
            delta = self.engine.tcp_cap_refusals - self._cap_folded
            if delta > 0:
                self._cap_refusal_child.inc(delta)
                self._cap_folded += delta

    def _fold_fastpath_metrics(self) -> None:
        """Fold the C fast path's monotonic counters into the Prometheus
        collectors (registered as a pre-scrape hook).  Deltas are taken
        against the last fold under a lock — concurrent scrapes must not
        double-count."""
        with self._fp_fold_lock:
            # Snapshot inside the lock: with it outside, two concurrent
            # scrapes could fold in order new-then-old, regressing the
            # delta baseline and double-counting on the next fold.
            stats = _fastio.fastpath_stats(self._fastpath)
            last = self._fp_folded
            hits_delta = stats["hits"] - last.get("hits", 0)
            if hits_delta > 0:
                self._cache_hit_child.inc(hits_delta)
            last["hits"] = stats["hits"]
            for qtype, s in stats["per_qtype"].items():
                children = self._children_for(qtype)
                prev = last.get(qtype)
                count_delta = s["count"] - (prev["count"] if prev else 0)
                if count_delta > 0:
                    children[0].inc(count_delta)
                    children[1].merge(
                        [c - (prev["lat_cells"][i] if prev else 0)
                         for i, c in enumerate(s["lat_cells"])],
                        s["lat_sum"] - (prev["lat_sum"] if prev else 0.0))
                    children[2].merge(
                        [c - (prev["size_cells"][i] if prev else 0)
                         for i, c in enumerate(s["size_cells"])],
                        s["size_sum"] - (prev["size_sum"] if prev else 0.0))
                last[qtype] = s

    def _children_for(self, qtype: int):
        """Pre-resolved (counter, latency, size) metric handles for a
        qtype — label-sort once, not per query; shared by the after-hook
        and the fast-path fold."""
        children = self._metric_children.get(qtype)
        if children is None:
            # 0xFFFF is the C stats catch-all past its per-qtype slots
            labels = {"type": "other" if qtype == 0xFFFF
                      else Type.name(qtype)}
            children = (self.request_counter.labelled(labels),
                        self.latency_histogram.labelled(labels),
                        self.size_histogram.labelled(labels))
            self._metric_children[qtype] = children
        return children

    def _fastpath_active(self) -> bool:
        """The C path bypasses Python entirely, so it must stand down
        whenever every query has to surface: per-query logging on, or a
        probe consumer attached."""
        return (not self.query_log
                and not self.p_req_start.enabled
                and not self.p_req_done.enabled)

    # -- after hook: metrics + query log (lib/server.js:509-591) --

    def _on_after(self, query: QueryCtx) -> None:
        query.stamp("log-after")
        lat_ms = query.latency_ms()
        if self.p_req_done.enabled:
            self.p_req_done.fire(lambda: {
                "id": query.request.id, "name": query.name(),
                "type": query.qtype_name(),
                "rcode": Rcode.name(query.rcode()),
                "latency_ms": round(lat_ms, 3), "bytes": query.bytes_sent,
            })
        level = logging.WARNING if lat_ms > SLOW_QUERY_MS else logging.INFO

        children = self._children_for(query.qtype())
        children[0].inc()
        children[1].observe(lat_ms / 1000.0)
        children[2].observe(query.bytes_sent)

        if not self.query_log and lat_ms <= SLOW_QUERY_MS:
            return
        if query.cached_summary is not None:
            ans, add = query.cached_summary
        else:
            ans = [self._summarize(r) for r in query.response.answers]
            add = [self._summarize(r) for r in query.response.additionals
                   if not isinstance(r, OPTRecord)]
        log_event(
            self.log, level, "DNS query",
            # request envelope built here, not per-query in _on_query:
            # most queries never log (queryLog off / fast), so the dict
            # work happens only on the slow/logged path
            req_id=query.request.id,
            client=query.src[0],
            port=f"{query.src[1]}/{query.protocol}",
            edns=query.request.edns is not None,
            **query.log_ctx,
            rcode=Rcode.name(query.rcode()),
            answers=ans,
            additional=add,
            latency=lat_ms,
            timers=query.times,
        )

    def _summarize(self, rec) -> object:
        if isinstance(rec, SRVRecord):
            return (f"SRV {strip_suffix('.' + self.dns_domain, rec.target)}"
                    f":{rec.port}")
        if isinstance(rec, ARecord):
            return (f"{strip_suffix('.' + self.dns_domain, rec.name)} "
                    f"A {rec.address}")
        d = {"type": Type.name(rec.rtype), "name": rec.name, "ttl": rec.ttl}
        if hasattr(rec, "target"):
            d["target"] = rec.target
        return d

    # -- lifecycle (lib/server.js:609-657) --

    async def start(self) -> None:
        if self.balancer_socket:
            await self.engine.listen_balancer(self.balancer_socket)
        self.udp_port = await self.engine.listen_udp(self.host, self.port)
        self.tcp_port = await self.engine.listen_tcp(
            self.host, self.port if self.port else self.udp_port)

    async def stop(self) -> None:
        await self.engine.close()


def create_server(**kwargs) -> BinderServer:
    return BinderServer(**kwargs)
