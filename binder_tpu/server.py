"""The binder server: transport engine + resolution + observability.

Port of the reference's ``createServer`` wiring (``lib/server.js:435-660``):
attaches the resolution engine to the transport engine's ``query`` hook,
and metrics + structured query logging to the ``after`` hook.  ``start()``
brings up UDP + TCP listeners and, when configured, the balancer UNIX
socket (``lib/server.js:609-653``).
"""
from __future__ import annotations

import asyncio
import errno as _errno
import json as _json
import logging
import os as _os
import re
import socket as _socket
import struct
import threading
import time
from urllib.parse import urlparse as _urlparse
from typing import Optional

try:  # native fast path (built by `make -C native`); optional
    from binder_tpu import _binderfastio as _fastio
except ImportError:
    _fastio = None

from binder_tpu.dns.query import QueryCtx
from binder_tpu.dns.server import DnsServer
from binder_tpu.dns.wire import (
    MAX_EDNS_PAYLOAD,
    MAX_UDP_PAYLOAD,
    ARecord,
    OPTRecord,
    PTRRecord,
    Rcode,
    SRVRecord,
    Type,
    WireError,
    encode_name,
    ip_from_reverse_name,
    patch_answer_wire,
    reverse_name_for_ip,
)
from binder_tpu.metrics.collector import (
    DEFAULT_SIZE_BUCKETS,
    DEFAULT_STAGE_BUCKETS,
    MetricsCollector,
)
from binder_tpu.resolver.answer_cache import AnswerCache
from binder_tpu.resolver.precompile import Precompiler
from binder_tpu.store.names import rec_parts as _names_rec_parts
from binder_tpu.resolver.engine import (
    DEFAULT_TTL,
    Resolver,
    SERVICE_CHILD_TYPES as _SERVICE_CHILD_TYPES,
    _record_ttl as _engine_record_ttl,
)
from binder_tpu.utils.jsonlog import JsonFormatter, log_event
from binder_tpu.utils.probes import ProbeProvider
from binder_tpu.verify import Verifier

METRIC_REQUEST_COUNTER = "binder_requests_completed"
METRIC_LATENCY_HISTOGRAM = "binder_request_latency_seconds"
METRIC_SIZE_HISTOGRAM = "binder_response_size_bytes"
# per-stage attribution: one histogram, labeled by stage, fed from the
# QueryCtx phase stamps at after-hook time — the scrapeable form of the
# query log's `timers` dict (same stage names)
METRIC_STAGE_HISTOGRAM = "binder_query_stage_seconds"

SLOW_QUERY_MS = 1000.0  # log at warn above this (lib/server.js:511-514)

# byte values a name label may contain for the native fast path; names
# outside this set are still served, just never through the C cache
# (keep in lockstep with fp_name_ok in native/fastio/fastpath.c)
_FP_NAME_OK = frozenset(
    b"abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_")


def strip_suffix(suffix: str, s: str) -> str:
    """Log redaction of the (long, constant) DNS domain
    (lib/server.js:60-65)."""
    if s.endswith(suffix):
        return s[:len(s) - len(suffix)] + "..."
    return s


# Pre-encoded EDNS echo for the raw lane: name 0, TYPE OPT(41),
# CLASS=payload 1232, TTL 0, RDLEN 0 — byte-identical to the generic
# path's _ECHO_OPT (dns/query.py) encoding.
_OPT_ECHO_WIRE = b"\x00" + struct.pack(">HHIH", 41, 1232, 0, 0)

# one label of a registered srvce/proto pair, exactly what one group of
# the engine's SRV_RE can match — zone SRV entries are only pushed for
# qnames the engine would parse back to the same service
_SRV_LABEL_RE = re.compile(r"^_[^_.]*$")

# rotation-variant ceiling, in lockstep with FP_MAX_VARIANTS
# (native/fastio/fpcore.h) — a push with more variants than the C side
# accepts would be silently rejected and the name never precompiled
_FP_MAX_VARIANTS = 8

# Record types the raw lane may answer directly: exactly the host-likes
# the resolver maps to a single A record (resolver/engine.py:213-216).
# 'service' (rotation, SRV) and 'database' (URL parse) take the generic
# path.
_LANE_HOST_TYPES = frozenset({
    "db_host", "host", "load_balancer", "moray_host", "redis_host",
    "ops_host", "rr_host",
})


def _rec_ttl(rec: tuple) -> int:
    """Deepest-object-wins TTL for a COMPACT record tuple
    (store/names.py) — sub-record TTL wins, else record TTL, else
    default; the compact invariant guarantees ints, so there is no
    garbage case to decline on."""
    parts = _names_rec_parts(rec)
    if parts[3] is not None:
        return parts[3]
    if parts[2] is not None:
        return parts[2]
    return DEFAULT_TTL


def _lane_ttl(record: dict, sub) -> Optional[int]:
    """Deepest-object-wins TTL (the one policy, engine._record_ttl:
    sub-record TTL wins, else record TTL, else default); None means the
    store value is garbage and the lane must decline to the generic
    path.  Shared by the A and PTR lane branches so the precedence
    cannot drift between them."""
    ttl = record.get("ttl")
    sttl = sub.get("ttl") if type(sub) is dict else None
    if sttl is not None:
        ttl = sttl
    elif ttl is None:
        ttl = DEFAULT_TTL
    return ttl if type(ttl) is int else None


def _fastpath_key_parts(rd: bool, edns: bool, payload: int, qtype: int,
                        qclass: int, qname_wire: bytes) -> bytes:
    """The native answer-cache key, from its components.

    SINGLE SOURCE OF THE LAYOUT on the Python side — both
    ``BinderServer._fastpath_key`` and the raw lane build through here.
    Must stay byte-for-byte with ``fp_build_key`` in
    native/fastio/fastpath.c and the balancer's copy (see
    docs/balancer-protocol.md):
    ``[flags rd|edns<<1][payload BE16][qtype BE16][qclass BE16][qname]``
    where qname is the wire-format name, lowercased.
    """
    return (bytes([(1 if rd else 0) | (2 if edns else 0)])
            + payload.to_bytes(2, "big") + qtype.to_bytes(2, "big")
            + qclass.to_bytes(2, "big") + qname_wire)


class BinderServer:
    def __init__(self, *, zk_cache, dns_domain: str,
                 datacenter_name: str = "",
                 recursion=None,
                 log: Optional[logging.Logger] = None,
                 collector: Optional[MetricsCollector] = None,
                 name: str = "binder",
                 host: str = "127.0.0.1", port: int = 53,
                 balancer_socket: Optional[str] = None,
                 query_log: bool = True,
                 cache_size: int = 10000,
                 cache_expiry_ms: int = 60000,
                 zone_precompile: bool = True,
                 answer_precompile: bool = False,
                 precompile_size: Optional[int] = None,
                 tcp_idle_timeout: Optional[float] = None,
                 max_tcp_conns: Optional[int] = None,
                 max_tcp_write_buffer: Optional[int] = None,
                 probes: Optional[ProbeProvider] = None,
                 flight_recorder=None,
                 degradation: Optional[dict] = None,
                 admission: Optional[dict] = None,
                 rrl: Optional[dict] = None,
                 verify: Optional[dict] = None,
                 reuse_port: bool = False,
                 announce: bool = True) -> None:
        self.log = log or logging.getLogger("binder.server")
        # introspection flight recorder (binder_tpu/introspect):
        # slow-query events from the after hook and lane, resolver
        # errors from the engine's error path
        self.recorder = flight_recorder
        self.host = host
        self.port = port
        # shard mode (binder_tpu/shard): N workers bind ONE port via
        # SO_REUSEPORT and the supervisor owns the canonical "service
        # started" announce lines — workers keep quiet so harnesses
        # never latch onto a group still forming
        self.reuse_port = reuse_port
        self.announce = announce
        self.dns_domain = dns_domain
        self.balancer_socket = balancer_socket
        self.collector = collector or MetricsCollector()
        # per-query logging can be disabled for high-qps deployments;
        # slow queries (>1s) are logged regardless
        self.query_log = query_log
        # encoded-answer cache (the reference's -s/-a flags, main.js:34-38)
        self.zk_cache = zk_cache
        self.answer_cache = AnswerCache(
            size=cache_size, expiry_ms=cache_expiry_ms,
            compiled_size=precompile_size,
            # tag/qname strings dedup against the mirror's own domain
            # objects (the interned-name pool architecture, ISSUE 7)
            intern=getattr(zk_cache, "canon", None))
        self.cache_hit_counter = self.collector.counter(
            "binder_answer_cache_hits", "encoded-answer cache hits")
        self._cache_hit_child = self.cache_hit_counter.labelled()
        self._fp_inval_total = 0   # C-side drops, updated at each fold
        self.collector.gauge(
            "binder_answer_cache_invalidations",
            "answer-cache entries dropped by per-name store invalidation"
        ).set_function(lambda: float(self.answer_cache.invalidations
                                     + self._fp_inval_total))

        self.request_counter = self.collector.counter(
            METRIC_REQUEST_COUNTER, "count of Binder requests completed")
        self.latency_histogram = self.collector.histogram(
            METRIC_LATENCY_HISTOGRAM,
            "total time to process Binder requests")
        self.size_histogram = self.collector.histogram(
            METRIC_SIZE_HISTOGRAM, "size in bytes of Binder responses",
            buckets=DEFAULT_SIZE_BUCKETS)
        self.stage_histogram = self.collector.histogram(
            METRIC_STAGE_HISTOGRAM,
            "per-stage decomposition of request processing time",
            buckets=DEFAULT_STAGE_BUCKETS)
        # per-qtype pre-resolved metric handles (label-sort once, not
        # per query); key is the numeric qtype
        self._metric_children: dict = {}
        # per-stage pre-resolved histogram handles, keyed by stage name
        self._stage_children: dict = {}

        # USDT analog: provider 'binder', probes op-req-start/op-req-done
        # fired with the query context (lib/server.js:24-29,472-474,516-518)
        self.probes = probes or ProbeProvider("binder")
        self.p_req_start = self.probes.probe("op-req-start")
        self.p_req_done = self.probes.probe("op-req-done")

        self.resolver = Resolver(zk_cache, dns_domain=dns_domain,
                                 datacenter_name=datacenter_name,
                                 recursion=recursion, log=self.log)

        # Degradation policy engine (binder_tpu/policy, docs/
        # degradation.md).  Off by default at this layer — main.py
        # turns both on from config (`degradation` / `admission`
        # blocks, default enabled) like the other production knobs.
        self._policy = None
        self._policy_task = None
        store = getattr(zk_cache, "store", None)
        if (degradation is not None
                and degradation.get("enabled", True) and store is not None):
            from binder_tpu.policy import DegradationPolicy
            self._policy = DegradationPolicy(
                store=store, zk_cache=zk_cache,
                max_staleness_s=float(degradation.get(
                    "maxStalenessSeconds", 300.0)),
                stale_ttl_clamp_s=int(degradation.get(
                    "staleTtlClampSeconds", 30)),
                exhausted_action=str(degradation.get(
                    "exhaustedAction", "servfail")),
                collector=self.collector, recorder=flight_recorder,
                log=self.log)
            # answers rendered under one staleness mode must never be
            # served under another: every transition flushes all cached
            # lanes (Python, compiled, native, balancer) via the epoch
            self._policy.on_transition(self._on_degradation_transition)
            self.resolver.policy = self._policy
        self._admission = None
        if admission is not None and admission.get("enabled", True):
            from binder_tpu.policy import AdmissionControl
            self._admission = AdmissionControl(
                max_inflight=int(admission.get("maxInflight", 512)),
                recursion_rate=float(admission.get(
                    "recursionRate", 50.0)),
                recursion_burst=float(admission.get(
                    "recursionBurst", 100.0)),
                collector=self.collector, recorder=flight_recorder,
                log=self.log)
            self.resolver.admission = self._admission
        # Response rate limiting (binder_tpu/policy/rrl.py): per-client-
        # prefix slip/drop at the UDP ingress.  Same config convention as
        # admission — None disables (direct construction / tests), a
        # config block (even empty) enables with defaults.
        self._rrl = None
        if rrl is not None:
            from binder_tpu.policy import ResponseRateLimiter
            self._rrl = ResponseRateLimiter.from_config(
                rrl,
                note_shed=(self._admission._note_shed
                           if self._admission is not None else None),
                recorder=flight_recorder, log=self.log)
        self._rrl_children: dict = {}
        self._rrl_folded: dict = {}
        if self._rrl is not None:
            for field, help_text in (
                ("responses", "UDP responses admitted by response rate "
                 "limiting"),
                ("slipped", "rate-limited UDP queries answered with a "
                 "TC=1 slip (client retries over TCP)"),
                ("dropped", "rate-limited UDP queries dropped silently"),
                ("evictions", "RRL prefix buckets evicted at the LRU "
                 "cap"),
                ("allowlisted", "responses passed by an RRL allowlist "
                 "match (never limited, never bucketed)"),
                ("adaptations", "adaptive-bucket rate doublings earned "
                 "by TCP-proven prefixes"),
                ("false_positives", "rate-limited responses charged to "
                 "a prefix later proven real by completed TCP retries "
                 "(the measured RRL false-positive count)"),
            ):
                child = self.collector.counter(
                    "binder_rrl_" + field + "_total", help_text).labelled()
                child.inc(0)   # series exists from scrape 1
                self._rrl_children[field] = child
            self.collector.gauge(
                "binder_rrl_buckets",
                "client prefixes currently tracked by response rate "
                "limiting"
            ).set_function(lambda: float(len(self._rrl._buckets)))
            self.collector.gauge(
                "binder_rrl_active",
                "1 while response rate limiting shed traffic recently "
                "(the hostile-flood posture; also closes the native "
                "fastpath gate)"
            ).set_function(lambda: 1.0 if self._rrl.hot() else 0.0)
            self.collector.gauge(
                "binder_rrl_adapted_buckets",
                "client prefixes holding an earned adaptive rate "
                "multiplier (TCP-proven NAT'd farms)"
            ).set_function(lambda: float(self._rrl.adapted_count()))
        if recursion is not None and hasattr(recursion, "engine_after"):
            # arm the recursion fast path: its future callback completes
            # the query AND runs the engine's after hook itself
            recursion.engine_after = self._engine_after_hook
        # multi-DC federation handle (binder_tpu/federation) — set by
        # main.py (or tests) after construction; read by the
        # introspector for the /status federation section
        self.federation = None

        # Serving-plane verification (binder_tpu/verify, ISSUE 16):
        # incremental invariant checks off the same per-name
        # invalidation feed the precompiler drains, a sampled
        # budgeted full-zone audit, and mutation-to-glass propagation
        # tracing.  Same config convention as admission/rrl: None
        # disables (direct construction / tests), a config block
        # (even empty) enables with defaults.
        self._verify: Optional[Verifier] = None
        # trace contexts for names awaiting a zone re-push, popped by
        # _zone_refresh to mark the native-install stage; bounded so a
        # mutation storm on an unserved zone cannot grow it
        self._zone_trace: dict = {}
        if verify is not None and verify.get("enabled", True):
            self._verify = Verifier(
                zk_cache=zk_cache, answer_cache=self.answer_cache,
                resolver=self.resolver,
                policy_mode=(self._policy.mode
                             if self._policy is not None else None),
                config=verify, collector=self.collector,
                recorder=flight_recorder, log=self.log)
            # the mirror stamps each mutation's trace context at
            # bump_gen and marks mirror-apply at invalidation fan-out
            zk_cache.tracer = self._verify.tracer

        # Mutation-time answer precompilation (resolver/precompile.py):
        # store mutations eagerly re-render the affected names' answers
        # into the AnswerCache's compiled table, so post-churn (and
        # seeded cold) queries are a dict probe + ID/flags patch instead
        # of an engine.resolve() pass.  Off by default at this layer —
        # main.py turns it on from config (`answerPrecompile`, default
        # true) like the other production knobs.
        self._precompiler: Optional[Precompiler] = None
        if answer_precompile and cache_size > 0:
            self._precompiler = Precompiler(
                resolver=self.resolver, answer_cache=self.answer_cache,
                zk_cache=zk_cache, summarize=self._summarize,
                collector=self.collector, recorder=flight_recorder,
                log=self.log, native_put=self._precompile_native_put,
                tracer=(self._verify.tracer
                        if self._verify is not None else None))
        if self._verify is not None:
            # the checker re-renders through the precompiler for the
            # compiled-bytes invariant (None: skip-counted, not silent)
            self._verify.precompiler = self._precompiler
        self._precompile_serve_child = self.collector.counter(
            "binder_precompile_serves",
            "queries answered from mutation-time precompiled entries"
        ).labelled()
        self._precompile_serve_child.inc(0)   # series exists from scrape 1
        self.engine = DnsServer(log=self.log, name=name,
                                tcp_idle_timeout=tcp_idle_timeout,
                                max_tcp_conns=max_tcp_conns,
                                max_tcp_write_buffer=max_tcp_write_buffer)
        self.engine.on_query = self._on_query
        self.engine.on_after = self._on_after
        self.engine.recorder = flight_recorder
        self.engine.admission = self._admission
        self.engine.rrl = self._rrl
        # the engine's cap-refusal log line is rate-limited, so the
        # counter is the only complete record — surface it in the scrape
        self._cap_refusal_child = self.collector.counter(
            "binder_tcp_cap_refusals",
            "TCP connections refused at the connection cap").labelled()
        self._cap_refusal_child.inc(0)   # series exists from scrape 1
        self._cap_folded = 0
        # late (async-completed) UDP responses dropped at a full socket
        # buffer — previously a silent debug line (ISSUE 7 satellite)
        late_drops = self.collector.counter(
            "binder_udp_late_drops_total",
            "late (async-completed) UDP responses dropped because the "
            "socket send buffer stayed full through the retry").labelled()
        late_drops.inc(0)                # series exists from scrape 1
        self.engine.late_drop_counter = late_drops
        # stream-lane counters (dns/stream.py TcpStats), folded at
        # scrape time like the cap refusals; every series exists from
        # scrape 1 so absence is always an exporter bug
        # (tools/lint.py validate_tcp_metrics pins the family)
        self._tcp_stat_children: dict = {}
        for field, help_text in (
            ("accepts", "TCP connections accepted"),
            ("fast_serves", "frames served via the accept fast path "
             "(connections not yet promoted to the pipelined protocol)"),
            ("promotions", "TCP connections promoted to the full "
             "pipelined protocol (kept sending after the first served "
             "burst)"),
            ("oneshot_closes", "TCP connections closed after serving "
             "without ever promoting (one-shot clients)"),
            ("idle_timeouts", "TCP connections dropped by the idle "
             "deadline"),
            ("slow_reader_drops", "TCP connections disconnected at the "
             "write-buffer cap (client not reading responses)"),
            ("coalesced_writes", "vectored TCP writes that carried "
             "more than one response frame"),
            ("coalesced_frames", "TCP response frames sent through "
             "coalesced vectored writes"),
            ("half_closes", "half-closed TCP connections held to "
             "serve owed responses"),
            ("rst_drops", "TCP connections dropped on reset/error "
             "mid-read"),
        ):
            child = self.collector.counter("binder_tcp_" + field,
                                           help_text).labelled()
            child.inc(0)
            self._tcp_stat_children[field] = child
        self._tcp_stats_folded: dict = {}
        self.collector.gauge(
            "binder_tcp_open_conns",
            "TCP client connections currently open"
        ).set_function(lambda: float(len(self.engine._tcp_conns)))
        self.collector.on_expose(self._fold_engine_counters)

        # Raw resolve lane: direct wire assembly for single-question A/IN
        # queries (see _raw_lane).  Policy strings mirror Resolver.resolve
        # exactly; the lane declines anything it can't prove simple.
        dd = self.resolver.dns_domain
        self._lane_suffix = ("." + dd) if dd else None
        self._lane_dcsuff = dd + "." + self.resolver.datacenter_name
        self.engine.raw_lane = self._raw_lane

        # Native fast path: answer-cache hits served inside the C UDP
        # drain (native/fastio/fastpath.c).  Python remains the source of
        # truth — completed answer-cache entries are pushed down in
        # _on_query, and the C-side counters fold into the same
        # Prometheus collectors at scrape time (_fold_fastpath_metrics).
        # Balancer answer-cache support: the generation report carries
        # the mirror *epoch* (full-rebuild counter), so the balancer
        # only drops everything when a re-mirror really happened;
        # ordinary mutations ride the per-name invalidate frames
        # broadcast from _on_store_invalidate
        # (docs/balancer-protocol.md control frames)
        self.engine.gen_source = self._epoch_source
        if hasattr(zk_cache, "on_mutation"):
            zk_cache.on_mutation(self.engine.notify_mutation)
        # Per-name invalidation: a mirrored mutation drops exactly the
        # answer-cache/fast-path entries whose dependency tag it touched
        # (MirrorCache.invalidate); the epoch (bumped on full rebuilds)
        # covers everything else.  One churning record no longer evicts
        # every cached answer.
        if hasattr(zk_cache, "on_invalidate"):
            zk_cache.on_invalidate(self._on_store_invalidate)

        self._fastpath = None
        self._fp_folded: dict = {}
        self._fp_last_stats: dict = {}   # per-scrape snapshot (gauges)
        self._fp_fold_lock = threading.Lock()
        if (_fastio is not None and cache_size > 0
                and hasattr(_fastio, "fastpath_new")):
            self._fastpath = _fastio.fastpath_new(
                cache_size, cache_expiry_ms,
                [float(b) for b in self.latency_histogram.buckets],
                [float(b) for b in self.size_histogram.buckets])
            self.engine.fastpath = self._fastpath
            self.engine.fastpath_gen = self._epoch_source
            self.engine.fastpath_gate = self._fastpath_active
            self.collector.on_expose(self._fold_fastpath_metrics)

        # Native query-log ring: with per-query logging ON (the
        # reference's always-on posture, lib/server.js:537-591) the fast
        # path previously stood down completely, forfeiting ~9x
        # throughput.  Instead, entries now carry pre-rendered JSON log
        # fragments, the C serve path appends one complete bunyan-style
        # line per serve to a byte ring, and Python drains the ring in
        # batches onto the SAME stream the JSON logger writes to — one
        # stream write per batch instead of one formatting pass per
        # query.  A serve that cannot produce its line (ring full, no
        # fragment) DECLINES to the Python path, which logs normally:
        # pressure degrades throughput, never drops log records.
        # Armed only when the server's logger actually ends in a
        # JsonFormatter stream (the production logger from make_logger);
        # otherwise the old stand-down gating applies unchanged.
        self._log_ring = False
        self._log_json_handlers: list = []
        self._log_flush_task: Optional[asyncio.Task] = None
        if (self.query_log and self._fastpath is not None
                and hasattr(_fastio, "fastpath_log_enable")
                and self.log.isEnabledFor(logging.INFO)):
            self._log_json_handlers = self._find_json_handlers()
            if self._log_json_handlers:
                try:
                    _fastio.fastpath_log_enable(
                        self._fastpath, self._native_log_prefix(),
                        1 << 20)
                    self._log_ring = True
                    self.engine.fastpath_log_flush = self._drain_native_log
                except ValueError:
                    self._log_json_handlers = []

        # Zone precompilation (fpcore.h zone table): finished answer
        # bodies for the dominant record shapes (host A, PTR) are pushed
        # into the C drain from the STORE MIRROR — at startup and on
        # every mirrored mutation — so even the first query for a name
        # never surfaces to Python.  The reference resolves every cold
        # name per query (lib/server.js:136); this is the rebuild's
        # NSD/Knot-style answer to that.  `zonePrecompile: false`
        # disables it (the bench uses that to keep an honest measurement
        # of the Python resolve path).
        self._zone_enabled = (
            zone_precompile and self._fastpath is not None
            and hasattr(_fastio, "fastpath_zone_put"))
        # churn-path coalescing: batched C invalidation + deferred zone
        # refills (see _on_store_invalidate)
        self._fp_inval_many = getattr(_fastio, "fastpath_invalidate_many",
                                      None)
        self._zone_dirty: set = set()
        self._zone_drain_pending = False
        self._zone_fill_task = None
        self.zone_serve_counter = self.collector.counter(
            "binder_zone_serves",
            "queries answered from precompiled zone entries")
        self._zone_serve_child = self.zone_serve_counter.labelled({})
        if self._fastpath is not None:
            # Residency gauges: operators watching a mirror fill (or an
            # epoch rebuild) can see the native tables converge.  All
            # four read the single snapshot _fold_fastpath_metrics takes
            # per scrape (it runs as a pre-expose hook) — one stats
            # build per scrape, not one per gauge.
            def _fp_stat(key):
                return lambda: float(self._fp_last_stats.get(key, 0))
            self.collector.gauge(
                "binder_zone_entries",
                "precompiled answers resident in the native zone tables"
            ).set_function(_fp_stat("zone_entries"))
            self.collector.gauge(
                "binder_zone_bytes",
                "bytes held by precompiled zone answer bodies"
            ).set_function(_fp_stat("zone_bytes"))
            self.collector.gauge(
                "binder_fastpath_entries",
                "entries resident in the native answer cache"
            ).set_function(_fp_stat("entries"))
            self.collector.gauge(
                "binder_fastpath_bytes",
                "bytes held by native answer-cache wires"
            ).set_function(_fp_stat("bytes"))

        # actual bound ports (for tests / ephemeral binds)
        self.udp_port: Optional[int] = None
        self.tcp_port: Optional[int] = None

    def _engine_after_hook(self, query: QueryCtx) -> None:
        """After-hook entry for self-completing paths (the recursion
        fast path) — identical semantics to the engine's post-task
        _after call."""
        self.engine._after(query)

    def _epoch_source(self) -> int:
        """The epoch every cached lane validates against — evaluated
        THROUGH the degradation policy, so a lazy state transition
        (and its epoch-bumping cache flush) lands before the epoch is
        read.  Without this ordering, the first post-session-loss
        query could serve an unclamped cached wire from the native
        drain before any Python path noticed the transition."""
        if self._policy is not None:
            self._policy.mode()
        return self.zk_cache.epoch

    def _on_degradation_transition(self, old: str, new: str) -> None:
        """Degradation state edge: flush every cached answer lane.  The
        epoch bump invalidates the Python answer cache, the compiled
        table, the native C caches, and (via the generation frame) the
        balancer — so a wire rendered fresh is never served into
        exhaustion and clamped-TTL stale wires never survive recovery."""
        self.zk_cache.invalidate_all(
            reason=f"degradation {old} -> {new}")

    async def _policy_tick_loop(self) -> None:
        """1 s degradation-policy evaluator: transitions (and their
        metrics / flight-recorder events) must fire on an idle binder
        too, not only when a query happens to ask."""
        while True:
            await asyncio.sleep(1.0)
            try:
                self._policy.tick()
            except Exception:
                self.log.exception("degradation policy tick failed")

    # -- query hook (lib/server.js:471-507); sync, may return an awaitable
    # for the recursion path (see DnsServer._dispatch) --

    def _on_query(self, query: QueryCtx):
        if self.query_log:
            # log lines need decoded answer summaries: response paths
            # that would shortcut decoding (recursion splice) must not
            query.want_log_detail = True
        if self.p_req_start.enabled:   # skip closure alloc when off
            self.p_req_start.fire(lambda: {
                "trace": query.trace_id,
                "id": query.request.id, "name": query.name(),
                "type": query.qtype_name(), "client": query.src[0],
                "protocol": query.protocol,
            })
        # Answer-cache fast path.  The key is built from the decoded
        # fields the response actually depends on — transport semantics
        # (truncation), RD (drives the recursion-vs-REFUSED split on
        # misses), question, EDNS presence and payload ceiling — NOT the
        # raw wire: wire bytes vary with per-packet EDNS options (DNS
        # cookies, padding) and ignored padding sections, which would
        # mint one key per packet and evict the real entries.
        key = None
        req = query.request
        if len(req.questions) == 1 and req.opcode == 0:
            q0 = req.questions[0]
            key = (query.udp_semantics, req.rd, q0.qtype, q0.qclass,
                   q0.name, req.edns is not None, req.max_udp_payload())
            # policy-aware epoch: a pending degradation transition must
            # flush the caches BEFORE this probe can hit
            cached = self.answer_cache.get(key, self._epoch_source())
            if cached is not None:
                wire, ans, add = cached
                self._cache_hit_child.inc()
                query.response.rcode = wire[3] & 0x0F  # for metrics/logs
                query.log_ctx["cached"] = True
                query.cached_summary = (ans, add)
                query.stamp("cache-hit")   # decode→probe→serve, whole hit
                query.respond_raw(wire)
                # promote-on-first-hit: a repeat proves the name is hot,
                # so hand the entry to the C fast path NOW (resolve-time
                # pushes made one-shot cold names pay the native-push
                # cost for entries never served again)
                if (query.udp_semantics and self._fastpath is not None
                        and self._fastpath_active()):
                    self._fastpath_push(key, self.zk_cache.epoch, query)
                return None

        # Mutation-time precompiled probe: a per-key miss whose answer
        # was re-rendered at mutation time (or seeded at start) serves
        # as a dict probe + ID/flags patch — the engine never runs.
        if key is not None and self._serve_compiled(query, key, q0):
            return None

        pending = self.resolver.handle(query)

        if (pending is None and key is not None and query.responded
                and query.wire is not None and not query.no_store
                and query.rcode() != Rcode.SERVFAIL):
            ans = [self._summarize(r) for r in query.response.answers]
            add = [self._summarize(r) for r in query.response.additionals
                   if not isinstance(r, OPTRecord)]
            # reused by _on_after for this query's own log line too —
            # summaries are built exactly once per resolve
            query.cached_summary = (ans, add)
            epoch = self.zk_cache.epoch
            # dependency tag: the store name this answer derives from
            # (set by the resolver at its lookup points); immutable
            # shapes (out-of-suffix REFUSED, NOTIMP) never consulted the
            # store, but tagging them with their own qname is harmless —
            # no mutation will ever emit it.  The native push happens at
            # the entry's first HIT (promote-on-first-hit above), never
            # here on the cold path.
            tag = query.dep_domain or q0.name
            rcode = query.rcode()
            self.answer_cache.put(
                key, epoch, (query.wire, ans, add),
                rotatable=len(query.response.answers) > 1, tag=tag,
                # negative answers (NXDOMAIN / NODATA) cache like
                # positives but are accounted separately; SERVFAIL is
                # excluded above — the never-cache rule
                negative=(rcode == Rcode.NXDOMAIN
                          or (rcode == Rcode.NOERROR
                              and not query.response.answers)),
                qkey=(q0.qtype, q0.name))
        return pending

    #: the client postures precompiled answers are installed under in
    #: the NATIVE answer cache: (rd, edns, effective payload).  These
    #: are the request shapes resolvers actually send (EDNS at the
    #: 1232 safe default, classic 512 without); anything else (odd
    #: payload advertisements, options) falls to the Python compiled
    #: probe, which serves every posture by patching.
    _NATIVE_POSTURES = ((False, False, MAX_UDP_PAYLOAD),
                        (True, False, MAX_UDP_PAYLOAD),
                        (False, True, 1232),
                        (True, True, 1232))

    def _precompile_native_put(self, qtype: int, qname: str, variants,
                               tag: str, rcode: int) -> None:
        """Install a precompiled answer set into the NATIVE answer
        cache, one entry per canonical client posture — the
        mutation-time analog of promote-on-first-hit.  The hit path IS
        the C drain; installing at mutation time makes the post-churn
        (and seeded cold) miss path take it from query one.  Pure
        optimization: every failure path simply leaves the name to the
        Python compiled probe.  Unlike query-path promotion, the push
        cost lands on the mutation drain, never on a query."""
        if self._fastpath is None:
            return
        qn = self._qname_wire(qname)
        tag_wire = self._qname_wire(tag)
        if qn is None or tag_wire is None:
            return
        # the C key builder only produces hostname-charset keys; an
        # install outside that set could never be probed
        i = 0
        while qn[i]:
            ll = qn[i]
            if not _FP_NAME_OK.issuperset(qn[i + 1:i + 1 + ll]):
                return
            i += 1 + ll
        frags = None
        if self._log_ring:
            # native serves must produce the same log line the Python
            # compiled serve would ({"precompiled": true} + summaries)
            frags = [self._log_frag({"precompiled": True}, rcode,
                                    v[2], v[3]) for v in variants]
            if any(f is None for f in frags):
                return                  # unloggable: stays in Python
        epoch = self.zk_cache.epoch
        for rd, edns, payload in self._NATIVE_POSTURES:
            wires = [patch_answer_wire(v[1] if edns else v[0], rd=rd)
                     for v in variants]
            if any(len(w) > payload for w in wires):
                continue    # truncation shapes: the generic path owns TC
            ckey = _fastpath_key_parts(rd, edns, payload, qtype, 1, qn)
            try:
                if frags is not None:
                    _fastio.fastpath_put(self._fastpath, ckey, qtype,
                                         epoch, wires, -1, tag_wire,
                                         frags)
                else:
                    _fastio.fastpath_put(self._fastpath, ckey, qtype,
                                         epoch, wires, -1, tag_wire)
            except (TypeError, ValueError, MemoryError) as e:
                self.log.debug("precompile native push skipped: %s", e)
                return

    def _serve_compiled(self, query: QueryCtx, key, q0) -> bool:
        """Serve one query from the compiled-answer table, if present:
        select the EDNS posture's pre-rendered wire, patch the RD bit
        (the ID and question case are patched by respond_raw as for any
        cached wire), respond, and install the result under the query's
        exact key so repeats take the plain hit path (and promote to the
        native fast path on their first hit, same economics as lazy
        entries).  Declines (False) when the table has no entry or the
        wire would need UDP truncation — the generic path owns those."""
        if q0.qclass != 1:
            return False
        epoch = self.zk_cache.epoch
        hit = self.answer_cache.get_compiled(q0.qtype, q0.name, epoch)
        if hit is None:
            return False
        (w0, w1, ans, add), rotatable, tag, negative = hit
        req = query.request
        wire = w1 if req.edns is not None else w0
        if query.udp_semantics and len(wire) > req.max_udp_payload():
            return False
        if req.rd:
            wire = patch_answer_wire(wire, rd=True)
        query.response.rcode = wire[3] & 0x0F   # for metrics/logs
        query.log_ctx["precompiled"] = True
        query.cached_summary = (ans, add)
        query.stamp("precompile-hit")   # decode→probe→patch, whole serve
        query.respond_raw(wire)
        self._precompile_serve_child.inc()
        try:
            self.answer_cache.put(
                key, epoch, (wire, ans, add), rotatable=rotatable,
                tag=tag, negative=negative, qkey=(q0.qtype, q0.name))
        except Exception:
            # response already sent: bookkeeping must not re-raise into
            # the dispatch path (it would SERVFAIL a served query)
            self.log.exception("compiled-serve bookkeeping failed")
        return True

    @staticmethod
    def _qname_wire(name: str) -> Optional[bytes]:
        """Lowercased wire label form of a dotted name — the dependency
        tag format shared with the C caches (fpcore.h fp_invalidate_tag).
        Delegates to the one real name encoder (wire.encode_name, which
        normalizes case and enforces label/name bounds); None for names
        that cannot appear as a C-side tag."""
        buf = bytearray()
        try:
            encode_name(name, buf, None)
        except (WireError, UnicodeEncodeError):
            return None
        return bytes(buf)

    def _on_store_invalidate(self, tags) -> None:
        """MirrorCache invalidation subscriber: drop the cached answers
        whose dependency tag a store mutation touched — in the Python
        answer cache, the native fast path (one batched table pass for
        the whole event, not one scan per tag), and (via opcode-1
        control frames) the balancer's cache.  The DROPS are synchronous
        (coherence: a stale answer must never survive its mutation);
        the zone RE-PUSHES are refill work and are deferred to a
        bounded dirty-set drain between serving batches, so a mutation
        burst can't stall the hot loop (VERDICT r4 weak 5).  Until a
        name's refresh runs, its queries resolve through the raw lane /
        generic path — slower, never stale."""
        wires = []
        # question shapes the drops touched — the precompiler's exact
        # re-render work list (concrete negative SRV qnames, postures)
        dropped: list = []
        for tag in tags:
            self.answer_cache.invalidate_tag(tag, dropped=dropped)
            wire = self._qname_wire(tag)
            if wire is not None:
                wires.append(wire)
        if wires and self._fastpath is not None:
            try:
                if self._fp_inval_many is not None:
                    self._fp_inval_many(self._fastpath, wires)
                else:   # older extension: per-tag fallback
                    for wire in wires:
                        _fastio.fastpath_invalidate(self._fastpath, wire)
            except (TypeError, ValueError):
                pass
        if wires:
            self.engine.notify_invalidate(wires)
        if self._precompiler is not None and dropped:
            # refill work, deferred and bounded like the zone drain; the
            # DROPS above were synchronous, so until a name's re-render
            # runs its queries resolve lazily — slower, never stale.
            # Only shapes with serving evidence (the dropped keys) are
            # re-rendered: churn on unqueried names costs nothing here.
            self._precompiler.enqueue(dropped)
        if self._verify is not None:
            # incremental verification rides the same feed (after the
            # drops and re-render enqueue: the checker sees the
            # post-mutation tables, never the stale ones)
            self._verify.enqueue_tags(tags)
            ctx = self._verify.tracer.current
            if ctx is not None and self._zone_enabled:
                zt = self._zone_trace
                for tag in tags:
                    zt[tag] = ctx
                while len(zt) > self._ZONE_TRACE_CAP:
                    del zt[next(iter(zt))]
        if self._zone_enabled:
            self._zone_dirty.update(tags)
            self._schedule_zone_drain()

    #: zone re-pushes drained per event-loop pass; bounds the refill
    #: work a mutation burst can inject between serving batches
    _ZONE_DRAIN_BATCH = 64

    #: pending native-install trace contexts retained (oldest dropped
    #: first — an evicted trace loses one stage sample, nothing else)
    _ZONE_TRACE_CAP = 4096

    def _schedule_zone_drain(self) -> None:
        if self._zone_drain_pending or not self._zone_dirty:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # no loop (synchronous setup paths): refresh inline
            dirty, self._zone_dirty = self._zone_dirty, set()
            for tag in dirty:
                self._zone_refresh(tag)
            return
        self._zone_drain_pending = True
        loop.call_soon(self._drain_zone_dirty)

    def _drain_zone_dirty(self) -> None:
        self._zone_drain_pending = False
        n = 0
        while self._zone_dirty and n < self._ZONE_DRAIN_BATCH:
            self._zone_refresh(self._zone_dirty.pop())
            n += 1
        if self._zone_dirty:
            # more pending: yield to I/O first (call_soon callbacks
            # added during a loop pass run on the NEXT pass)
            self._schedule_zone_drain()

    # -- zone precompilation (fpcore.h zone table) --

    def _zone_refresh(self, name: str) -> None:
        """(Re-)push the precompiled answer for one store name, if the
        mirror currently resolves it to a shape the zone table serves.
        Stale entries were already dropped by tag invalidation; absent
        or ineligible names simply stay un-pushed and resolve through
        the raw lane / generic path."""
        ctx = self._zone_trace.pop(name, None)
        try:
            if name.endswith(".in-addr.arpa") or name.endswith(".ip6.arpa"):
                if name.endswith(".ip6.arpa"):
                    # v6 reverse: canonical nibble parse; the PTR body
                    # is address-family-agnostic once the owner is found
                    ip = ip_from_reverse_name(name)
                    if ip is None:
                        return
                else:
                    parts = name.split(".")
                    if len(parts) < 3:
                        return
                    ip = ".".join(reversed(parts[:-2]))
                owner = self.zk_cache.reverse_lookup(ip)
                if owner is not None:
                    self._zone_push_ptr(name, owner)
            else:
                node = self.zk_cache.lookup(name)
                if node is None:
                    pass
                elif (type(node.rec) is dict
                        and node.rec.get("type") == "service"):
                    self._zone_push_service_a(name, node)
                    self._zone_push_service_srv(name, node)
                else:
                    self._zone_push_a(name, node)
        except Exception:
            # zone fill is an optimization: a push failure must never
            # break the mutation path that feeds it
            self.log.exception("zone push failed for %s", name)
        if ctx is not None and self._verify is not None:
            # the zone lane finished with this name — for a mutation's
            # trace that is "the glass shows it" (even a now-ineligible
            # name: its stale native entry is gone, which is the state
            # the zone table should serve)
            self._verify.tracer.observe("native-install", ctx)

    # -- chaos injection hooks (chaos/plan.py corrupt-answer /
    # drop-reverse; the driver dispatches on these method names) --

    def corrupt_answer(self, qname: Optional[str] = None):
        """Flip one byte mid-wire in a compiled-table entry's first
        rotation variant.  Direct table corruption fires NO
        invalidation — only the verify audit's compiled-bytes walk can
        find it, which is exactly what the chaos action exists to
        prove.  Returns the corrupted ``(qtype, qname)`` or None."""
        for ckey, e in self.answer_cache._compiled.items():
            if qname is not None and ckey[1] != qname:
                continue
            variants = e[2]
            if not variants:
                continue
            v = variants[0]
            if len(v[0]) <= 12:
                continue                # header-only wire: nothing to flip
            w0 = bytearray(v[0])
            w0[len(w0) // 2] ^= 0xFF
            variants[0] = (bytes(w0),) + tuple(v[1:])
            self.log.warning("chaos: corrupted compiled answer for %s",
                             ckey[1])
            return ckey
        return None

    def drop_reverse(self, ip: Optional[str] = None):
        """Delete one reverse-map entry without touching the forward
        node — the forward/reverse coherence break the ptr-coherence
        audit must catch (no invalidation fires here either).
        Returns the dropped address or None."""
        rl = self.zk_cache.rev_lookup
        if ip is None:
            ip = next(iter(rl), None)
        if ip is None or ip not in rl:
            return None
        node = rl.pop(ip)
        self.log.warning("chaos: dropped reverse entry %s -> %s",
                         ip, getattr(node, "domain", "?"))
        return ip

    def _zone_host_shape(self, node):
        """(record, sub, packed_addr, ttl) when `node` is a host-like
        record the raw lane would answer, else None — the eligibility
        rules are _raw_lane's, verbatim, so the zone table can never
        answer a shape the lane would decline."""
        rec = node.rec
        if type(rec) is tuple:
            # compact host-like: the only decline left is the address
            # canonicality check (TTLs are ints by invariant)
            if rec[0] not in _LANE_HOST_TYPES:
                return None
            packed = BinderServer._zone_packed_addr(rec[1])
            if packed is None:
                return None
            return rec, None, packed, _rec_ttl(rec)
        rt = rec.get("type") if type(rec) is dict else None
        if rt not in _LANE_HOST_TYPES:
            return None
        sub = rec.get(rt)
        if type(sub) is not dict:
            return None
        return BinderServer._zone_a_tail(rec, sub, sub.get("address"))

    @staticmethod
    def _zone_packed_addr(addr):
        """Canonical-dotted-quad check shared by every zone push —
        returns the packed address, or None to decline to Python.  ONE
        copy, so the rule cannot drift between the host, database, and
        service member paths."""
        if type(addr) is not str:
            return None
        try:
            packed = _socket.inet_aton(addr)
        except (OSError, TypeError):
            return None
        if _socket.inet_ntoa(packed) != addr:
            return None
        return packed

    @staticmethod
    def _zone_a_tail(record, sub, addr):
        """Validation tail for the single-A shapes (host-likes,
        database): canonical address + int TTL, or decline.  Returns
        the full (record, sub, packed, ttl) shape so callers are a
        single return."""
        packed = BinderServer._zone_packed_addr(addr)
        if packed is None:
            return None
        ttl = _lane_ttl(record, sub)
        if ttl is None:
            return None
        return record, sub, packed, ttl

    @staticmethod
    def _zone_database_shape(record):
        """The database branch of engine.resolve — one A record whose
        address is the hostname of the ``primary`` URL
        (lib/server.js:295-305) — when it would encode cleanly, else
        None (non-IP hostnames and malformed URLs stay in Python)."""
        sub = record.get("database")
        if type(sub) is not dict:
            return None
        primary = sub.get("primary", "")
        if type(primary) is not str:
            return None                 # urlparse(non-str) raises
        try:
            addr = _urlparse(primary).hostname
        except ValueError:
            return None
        return BinderServer._zone_a_tail(record, sub, addr)

    def _zone_push_a(self, name: str, node) -> None:
        """Precompile the A answer for a host-like or database record
        (the raw lane's A branch plus engine.resolve's database branch,
        done once at mutation time instead of per query)."""
        if not self._zone_suffix_ok(name):
            return
        rec = node.rec
        if type(rec) is dict and rec.get("type") == "database":
            shape = self._zone_database_shape(rec)
        else:
            shape = self._zone_host_shape(node)
        if shape is None:
            return
        _record, _sub, packed, ttl = shape
        qn = self._qname_wire(name)
        if qn is None:
            return
        body = (b"\xc0\x0c\x00\x01\x00\x01"
                + struct.pack(">IH", ttl & 0xFFFFFFFF, 4) + packed)
        frags = None
        if self._log_ring:
            # zone serves replace what Python would resolve fresh —
            # the fragment mirrors the resolve-path log line
            addr = _socket.inet_ntoa(packed)
            frags = [self._log_frag(
                {"query": {"srv": None, "name": name, "type": "A"}},
                Rcode.NOERROR,
                [self._summarize(ARecord(name=name, ttl=ttl,
                                         address=addr))], [])]
            if frags[0] is None:
                return
        try:
            self._zone_put(b"\x00\x01\x00\x01" + qn, 1, [body], qn,
                           0, frags)
        except (TypeError, ValueError, MemoryError) as e:
            self.log.debug("zone A push skipped for %s: %s", name, e)

    def _zone_suffix_ok(self, name: str) -> bool:
        """The raw lane's dnsDomain suffix policy (a doubled suffix is
        REFUSED, never answered) — shared by every forward zone push."""
        dd_suffix = self._lane_suffix
        if dd_suffix is None or not name.endswith(dd_suffix):
            return False
        stripped = name[:-len(dd_suffix)]
        dd = self.resolver.dns_domain
        return not (stripped == dd or stripped.endswith(dd_suffix)
                    or stripped == self._lane_dcsuff
                    or stripped.endswith("." + self._lane_dcsuff))

    @staticmethod
    def _zone_service_ttl(record):
        """``(s, ttl)`` from a service record — the sub-record after the
        nested-historical-format unwrap plus the engine's TTL precedence
        (engine.resolve + _resolve_service head) — or None when the
        shape would not resolve as a service."""
        if not (type(record) is dict
                and type(record.get("service")) is dict):
            return None                 # engine SERVFAILs: decline
        s = record["service"]
        ttl = _engine_record_ttl(record, s)
        if type(s.get("service")) is dict:
            s = s["service"]            # nested historical format
        if s.get("ttl") is not None:
            ttl = s["ttl"]
        if type(ttl) is not int:
            return None
        return s, ttl

    def _zone_service_members(self, node, ttl):
        """Validated member list ``[(knode, ksub, packed_addr, rttl)]``
        for a service node — the one place the member eligibility rules
        live, consumed by both the plain-A and the SRV push so the two
        zone paths cannot drift.  None when the generic path would
        SERVFAIL mid-set or a value would fail to encode (decline to
        Python); addressless or foreign-typed kids are skipped exactly
        like engine._resolve_service does."""
        members = []
        for knode in node.children:
            kr = knode.rec
            if type(kr) is tuple:
                # compact member (store/names.py): address present and
                # TTLs int by invariant; no ports key — the SRV push
                # falls back to the service-level default port
                if kr[0] not in _SERVICE_CHILD_TYPES:
                    continue
                packed = self._zone_packed_addr(kr[1])
                if packed is None:
                    return None         # encode would fail: decline
                parts = _names_rec_parts(kr)
                rttl = parts[3] if parts[3] is not None else (
                    parts[2] if parts[2] is not None else ttl)
                members.append((knode, None, packed, rttl))
                continue
            if not (type(kr) is dict
                    and kr.get("type") in _SERVICE_CHILD_TYPES):
                continue                # engine filters these out too
            ksub = kr.get(kr["type"])
            if type(ksub) is not dict:
                return None             # engine SERVFAILs mid-set
            addr = ksub.get("address")
            if addr is None:
                continue                # engine skips addressless kids
            packed = self._zone_packed_addr(addr)
            if packed is None:
                return None             # encode would fail: decline
            rttl = _engine_record_ttl(kr, ksub, ttl)
            if type(rttl) is not int:
                return None
            members.append((knode, ksub, packed, rttl))
        return members

    def _zone_push_service_a(self, name: str, node) -> None:
        """Precompile the plain-A rotation for a service record
        (engine._resolve_service's A branch, done once at mutation time):
        one variant per cyclic rotation of the member set, so serves
        round-robin like the shuffled generic path.  Declines (leaving
        the Python path authoritative) on anything _resolve_service
        would not answer as a plain multi-A set: invalid child records
        (SERVFAIL), empty member sets (NODATA), non-int TTLs,
        non-canonical addresses."""
        if not self._zone_suffix_ok(name):
            return
        head = self._zone_service_ttl(node.data)
        if head is None:
            return
        _s, ttl = head
        members = self._zone_service_members(node, ttl)
        if not members:
            return                      # NODATA shape: Python answers
        if len(members) > Precompiler.MAX_SET_RECORDS:
            return      # oversize rotation set: lazy (see precompile.py)
        answers = [
            (b"\xc0\x0c\x00\x01\x00\x01"
             + struct.pack(">IH", min(ttl, rttl) & 0xFFFFFFFF, 4)
             + packed)
            for _knode, _ksub, packed, rttl in members]
        qn = self._qname_wire(name)
        if qn is None:
            return
        nv = min(len(answers), _FP_MAX_VARIANTS)
        bodies = [b"".join(answers[i:] + answers[:i]) for i in range(nv)]
        frags = None
        if self._log_ring:
            # per-variant summaries rotate in lockstep with the bodies
            sums = [self._summarize(ARecord(
                        name=name, ttl=min(ttl, rttl),
                        address=_socket.inet_ntoa(packed)))
                    for _knode, _ksub, packed, rttl in members]
            ctx = {"query": {"srv": None, "name": name, "type": "A"}}
            frags = [self._log_frag(ctx, Rcode.NOERROR,
                                    sums[i:] + sums[:i], [])
                     for i in range(nv)]
            if any(f is None for f in frags):
                return
        try:
            self._zone_put(b"\x00\x01\x00\x01" + qn, len(answers),
                           bodies, qn, 0, frags)
        except (TypeError, ValueError, MemoryError) as e:
            self.log.debug("zone service push skipped for %s: %s",
                           name, e)

    def _zone_push_service_srv(self, name: str, node) -> None:
        """Precompile the SRV answer set for a service record under its
        registered ``srvce.proto.name`` qname (engine._resolve_service's
        SRV branch): per member per port an SRV answer at the
        service-level TTL, plus one A additional per member at the
        member TTL, rotating together.  The dependency tag is the
        service NODE name — not the SRV qname — so these entries live in
        the C side's alien table and are invalidated by its bounded
        scan.  Negative SRV shapes (wrong srvce/proto → NXDOMAIN, SRV on
        a non-service → NODATA+SOA, malformed qnames → REFUSED) are
        never pushed and keep resolving through Python."""
        if not self._zone_suffix_ok(name):
            return
        head = self._zone_service_ttl(node.data)
        if head is None:
            return
        s, ttl = head
        srvce, proto = s.get("srvce"), s.get("proto")
        # Only qnames the engine's SRV_RE would parse back to exactly
        # this service can ever match this entry — and only LOWERCASE
        # registrations: decoded query labels arrive lowercased
        # (wire.py:185) and the engine compares them against the stored
        # strings exactly, so an uppercase-registered srvce/proto is
        # unmatchable (NXDOMAIN for every query) and must never be
        # precompiled under its lowercased qname.
        if not (type(srvce) is str and _SRV_LABEL_RE.match(srvce)
                and srvce == srvce.lower()
                and type(proto) is str and _SRV_LABEL_RE.match(proto)
                and proto == proto.lower()):
            return
        default_port = s.get("port")
        raw_members = self._zone_service_members(node, ttl)
        if not raw_members:
            return                      # empty set: NOERROR via Python
        if len(raw_members) > Precompiler.MAX_SET_RECORDS:
            return      # oversize rotation set: lazy (see precompile.py)
        members = []
        for knode, ksub, packed, rttl in raw_members:
            # compact members (ksub None) carry no ports key by
            # invariant: the service-level default port applies
            ports = ksub.get("ports") if type(ksub) is dict else None
            if not ports:
                ports = [default_port]
            if type(ports) is not list:
                return
            target = f"{knode.name}.{name}"
            tw = self._qname_wire(target)
            if tw is None:
                return
            ans = b""
            srv_sums = []
            for p in ports:
                if type(p) is not int or not 0 <= p <= 0xFFFF:
                    return              # encode would fail: decline
                # SRV rdata: priority 0, weight 10 (engine constants),
                # port, uncompressed target (RFC 2782 forbids pointers
                # in SRV rdata)
                ans += (b"\xc0\x0c\x00\x21\x00\x01"
                        + struct.pack(">IH", ttl & 0xFFFFFFFF,
                                      6 + len(tw))
                        + struct.pack(">HHH", 0, 10, p) + tw)
                if self._log_ring:
                    srv_sums.append(self._summarize(SRVRecord(
                        name=name, ttl=ttl, priority=0, weight=10,
                        port=p, target=target)))
            # summaries rendered only in the logged posture — churn-path
            # zone refreshes in the log-off posture must not pay for them
            add_sum = (self._summarize(ARecord(
                name=target, ttl=rttl,
                address=_socket.inet_ntoa(packed)))
                if self._log_ring else None)
            add = (tw + b"\x00\x01\x00\x01"
                   + struct.pack(">IH", rttl & 0xFFFFFFFF, 4) + packed)
            members.append((ans, add, len(ports), srv_sums, add_sum))
        qn = self._qname_wire(f"{srvce}.{proto}.{name}")
        tag = self._qname_wire(name)
        if qn is None or tag is None:
            return
        ancount = sum(m[2] for m in members)
        arcount = len(members)
        if ancount > 0xFFFF:
            return
        nv = min(len(members), _FP_MAX_VARIANTS)
        bodies = []
        for i in range(nv):
            rot = members[i:] + members[:i]
            bodies.append(b"".join(m[0] for m in rot)
                          + b"".join(m[1] for m in rot))
        frags = None
        if self._log_ring:
            ctx = {"query": {"srv": f"{srvce}.{proto}", "name": name,
                             "type": "SRV"}}
            frags = []
            for i in range(nv):
                rot = members[i:] + members[:i]
                frags.append(self._log_frag(
                    ctx, Rcode.NOERROR,
                    [s for m in rot for s in m[3]],
                    [m[4] for m in rot]))
            if any(f is None for f in frags):
                return
        try:
            self._zone_put(b"\x00\x21\x00\x01" + qn, ancount, bodies,
                           tag, arcount, frags)
        except (TypeError, ValueError, MemoryError) as e:
            self.log.debug("zone SRV push skipped for %s: %s", name, e)

    def _zone_push_ptr(self, rev_name: str, owner) -> None:
        """Precompile the PTR answer for a reverse name (the raw lane's
        PTR branch; NO dnsDomain suffix policy on the reverse tree,
        lib/server.js:67-134)."""
        shape = self._zone_host_shape(owner)
        if shape is None:
            return
        _record, _sub, _packed, ttl = shape
        target = owner.domain
        if target.endswith(".arpa"):
            return                      # parity with the lane's decline
        tw = self._qname_wire(target)
        if tw is None:
            return
        qn = self._qname_wire(rev_name)
        if qn is None:
            return
        body = (b"\xc0\x0c\x00\x0c\x00\x01"
                + struct.pack(">IH", ttl & 0xFFFFFFFF, len(tw)) + tw)
        frags = None
        if self._log_ring:
            ip = ".".join(reversed(rev_name.split(".")[:-2]))
            frags = [self._log_frag(
                {"query": {"ip": ip, "type": "PTR"}}, Rcode.NOERROR,
                [self._summarize(PTRRecord(name=rev_name, ttl=ttl,
                                           target=target))], [])]
            if frags[0] is None:
                return
        try:
            self._zone_put(b"\x00\x0c\x00\x01" + qn, 1, [body], qn,
                           0, frags)
        except (TypeError, ValueError, MemoryError) as e:
            self.log.debug("zone PTR push skipped for %s: %s", rev_name, e)

    def _zone_put(self, zkey: bytes, ancount: int, bodies, tag: bytes,
                  arcount: int, frags) -> None:
        """The one zone_put call site: appends the per-variant log
        fragments only when present, so an older compiled extension
        (pre-log-ring arity) keeps accepting log-off pushes."""
        if frags is not None:
            _fastio.fastpath_zone_put(self._fastpath, zkey,
                                      self.zk_cache.epoch, ancount,
                                      bodies, tag, arcount, frags)
        else:
            _fastio.fastpath_zone_put(self._fastpath, zkey,
                                      self.zk_cache.epoch, ancount,
                                      bodies, tag, arcount)

    #: per-pass wall budget for the chunked zone fill / seed walks
    _FILL_BUDGET_S = 0.002

    def _zone_fill(self) -> None:
        """Walk the mirror and push every eligible precompiled answer —
        run at server start for mirrors built before this server
        subscribed to invalidation events (later arrivals ride
        _on_store_invalidate).  Small zones fill inline (the historical
        semantics); at zone scale the walk moves to a time-budgeted
        background task so serving starts immediately and the fill
        streams in behind it (un-filled names resolve through the
        raw lane / generic path — slower, never wrong)."""
        if not self._zone_enabled:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        nodes = self.zk_cache.nodes
        reserve = getattr(_fastio, "fastpath_zone_reserve", None)
        if reserve is not None and len(nodes) > 1024:
            # presize the native zone table for the fill (one A + one
            # PTR entry per host): growth rehashes are O(table) and the
            # largest one at zone scale measured ~370 ms — an
            # event-loop stall mid-serving, not a hiccup
            try:
                reserve(self._fastpath, 2 * len(nodes))
            except (TypeError, ValueError, MemoryError) as e:
                self.log.debug("zone-table reserve skipped: %s", e)
        if loop is not None and len(nodes) > Precompiler.SEED_INLINE_MAX:
            self._zone_fill_task = loop.create_task(
                self._zone_fill_chunked())
            return
        for domain in list(nodes):
            self._zone_fill_one(domain)

    def _zone_fill_one(self, domain: str) -> None:
        node = self.zk_cache.nodes.get(domain)
        if node is None:
            return                      # left the mirror mid-walk
        self._zone_refresh(domain)
        ip = node.ip
        if ip and type(ip) is str:
            if ":" in ip:
                # v6 (already canonical via TreeNode.ip): precompile
                # the ip6.arpa PTR alongside the forward name
                try:
                    rev = reverse_name_for_ip(ip)
                except ValueError:
                    return
                self._zone_refresh(rev)
                return
            parts = ip.split(".")
            if len(parts) == 4 and all(p.isdigit() for p in parts):
                self._zone_refresh(
                    ".".join(reversed(parts)) + ".in-addr.arpa")

    async def _zone_fill_chunked(self) -> None:
        domains = list(self.zk_cache.nodes)
        self.log.info("zone fill: %d names, chunked", len(domains))
        started = time.perf_counter()
        i = 0
        while i < len(domains):
            t0 = time.perf_counter()
            while i < len(domains) \
                    and time.perf_counter() - t0 < self._FILL_BUDGET_S:
                self._zone_fill_one(domains[i])
                i += 1
            await asyncio.sleep(0)
        self.log.info("zone fill done: %d names in %.1fs", len(domains),
                      time.perf_counter() - started)

    def _fastpath_push(self, key, epoch: int, query: QueryCtx) -> None:
        """Promote an answer-cache entry to the native fast path (on
        its first hit — see _on_query).  The C key is built from the
        request's raw qname bytes so both key builders see identical
        input; names outside the hostname charset (which Python decodes
        with replacement) are skipped — they keep being served by the
        Python path."""
        claimed = self.answer_cache.take_push(key, epoch)
        if claimed is None:
            return
        variants, tag = claimed
        ckey = self._fastpath_key(query)
        if ckey is None:
            return
        tag_wire = self._qname_wire(tag)
        if tag_wire is None:
            return                      # not invalidatable: keep in Python
        wires = [v[0] for v in variants]
        frags = None
        if self._log_ring:
            # native serves of this entry are cache hits; the Python
            # hit path logs exactly {cached: true} + rcode + summaries
            # (_on_query cache-hit branch + _on_after), so the fragment
            # mirrors that shape per variant
            frags = [self._log_frag({"cached": True}, w[3] & 0x0F, a, d)
                     for (w, a, d) in variants]
            if any(f is None for f in frags):
                return                  # unloggable: stays in Python
        ttl_ms = self.answer_cache.remaining_ttl_ms(key, epoch)
        ttl_arg = -1 if ttl_ms is None else int(ttl_ms)
        try:
            # frags appended only when present so an older compiled
            # extension keeps accepting log-off pushes
            if frags is not None:
                _fastio.fastpath_put(self._fastpath, ckey, query.qtype(),
                                     epoch, wires, ttl_arg, tag_wire,
                                     frags)
            else:
                _fastio.fastpath_put(self._fastpath, ckey, query.qtype(),
                                     epoch, wires, ttl_arg, tag_wire)
        except (TypeError, ValueError, MemoryError) as e:
            self.log.debug("fastpath push skipped: %s", e)

    @staticmethod
    def _fastpath_key(query: QueryCtx) -> Optional[bytes]:
        # layout must match fp_build_key in native/fastio/fastpath.c:
        # [flags rd|edns<<1][payload BE16][qtype BE16][qclass BE16][qname]
        raw = query.raw
        req = query.request
        if raw is None or len(raw) < 17:
            return None
        off = 12
        try:
            while True:
                label_len = raw[off]
                if label_len == 0:
                    off += 1
                    break
                if label_len & 0xC0:
                    return None   # compressed question name: C punts too
                label = raw[off + 1:off + 1 + label_len]
                if (len(label) != label_len
                        or not _FP_NAME_OK.issuperset(label)):
                    return None
                off += 1 + label_len
                if off - 12 > 255:
                    return None
        except IndexError:
            return None
        q0 = req.questions[0]
        return _fastpath_key_parts(req.rd, req.edns is not None,
                                   req.max_udp_payload(), q0.qtype,
                                   q0.qclass, raw[12:off].lower())

    def _raw_lane(self, data: bytes, src, protocol: str, send,
                  client_transport: Optional[str] = None) -> bool:
        """Direct-assembly resolve for the dominant query shapes: one
        A/IN or PTR/IN question, optionally with a bare EDNS OPT.

        The generic path costs ~60µs per cold name (Message decode,
        QueryCtx, resolver, Message encode); this lane answers the same
        shapes in a few µs by patching the request wire: header rewrite,
        verbatim question echo, one compression-pointer A or PTR record.
        It mirrors ``Resolver.resolve`` / ``Resolver.resolve_ptr``
        policy exactly for the shapes it accepts — suffix /
        doubled-suffix REFUSED (forward only; the reverse tree has no
        suffix policy), store-down SERVFAIL, TTL precedence,
        REFUSED-not-NXDOMAIN on misses (lib/server.js:227-241) — and is
        differential-tested against the generic path
        (tests/test_raw_lane.py).  Everything else — other qtypes, EDNS
        options, service/database records, the recursion handoff,
        invalid records, responses that would need UDP truncation,
        query-log/probes active — returns False and takes the generic
        path, so divergence is impossible for declined shapes.

        The question section is echoed with the requester's original
        case (dns0x20), matching the generic path's echo in
        QueryCtx._echo_question_case.
        """
        if (self.query_log or self.p_req_start.enabled
                or self.p_req_done.enabled):
            return False
        if self._policy is not None and self._policy.mode() != "fresh":
            # degraded serving (TTL clamp, withhold-past-cap) is the
            # generic path's job; the lane declines rather than
            # duplicating the policy matrix (docs/degradation.md)
            return False
        dd_suffix = self._lane_suffix
        if dd_suffix is None:
            return False
        n = len(data)
        if n < 17:
            return False
        # header: QR / opcode / TC must be clear; QD=1; AN=NS=0; AR<=1
        if data[2] & 0xFA:
            return False
        if (data[4] or data[5] != 1 or data[6] or data[7] or data[8]
                or data[9] or data[10] or data[11] > 1):
            return False
        start = time.monotonic()
        # question name: case-preserving walk, charset-validated (the
        # charset equals the resolver's NAME_RE alphabet, so names the
        # lane declines here are exactly the generic path's
        # invalid-name REFUSED shapes plus non-ASCII oddities)
        labels = []
        off = 12
        ok = _FP_NAME_OK.issuperset
        while True:
            ll = data[off]
            if ll == 0:
                off += 1
                break
            if ll & 0xC0:
                return False           # compressed qname
            end = off + 1 + ll
            if end + 1 > n:
                return False
            if not ok(data[off + 1:end]):
                return False
            labels.append(data[off + 1:end])
            off = end
            if off - 12 > 255:
                return False
        if off + 4 > n:
            return False
        qtype_b = data[off:off + 4]
        if qtype_b == b"\x00\x01\x00\x01":       # A / IN
            qtype_val = 1
        elif qtype_b == b"\x00\x0c\x00\x01":     # PTR / IN
            qtype_val = 12
        else:
            return False
        q_end = off + 4
        edns = False
        payload = MAX_UDP_PAYLOAD
        if data[11]:
            # exactly one bare OPT: root name, TYPE 41, version 0, no
            # RDATA (EDNS options vary per packet and take the generic
            # path; so do nonzero versions)
            if q_end + 11 != n or data[q_end] != 0:
                return False
            otype, ocls = struct.unpack_from(">HH", data, q_end + 1)
            if otype != 41 or data[q_end + 6] != 0:
                return False
            if data[q_end + 9] or data[q_end + 10]:
                return False
            # same floor/clamp as Message.max_udp_payload — shared
            # constants so the copies cannot drift
            if ocls >= MAX_UDP_PAYLOAD:
                payload = min(ocls, MAX_EDNS_PAYLOAD)
            edns = True
        elif q_end != n:
            return False               # trailing bytes
        try:
            name = b".".join(labels).lower().decode("ascii")
        except UnicodeDecodeError:
            return False

        rd_flag = data[2] & 0x01
        udp_sem = (protocol == "udp"
                   or (protocol == "balancer" and client_transport != "tcp"))
        # the key layout must stay byte-for-byte with _on_query's
        key = (udp_sem, bool(rd_flag), qtype_val, 1, name, edns, payload)
        cache = self.zk_cache
        epoch = cache.epoch
        hit = self.answer_cache.get(key, epoch)
        if hit is not None:
            cached = hit[0]
            # patch in this requester's id AND question bytes: cached
            # wires store the question lowercased (see the put below), so
            # echoing the requester's own bytes keeps dns0x20 validators
            # happy; same name/qtype keyed -> identical section length
            wire = (data[:2] + cached[2:12] + data[12:q_end]
                    + cached[q_end:])
            send(wire)
            try:
                self._cache_hit_child.inc()
                self._lane_finish(data, src, protocol, start, wire,
                                  wire[3] & 0x0F, edns, hit[1], hit[2],
                                  qtype=qtype_val, cached=True)
                # promote-on-first-hit: the repeat proves the name hot;
                # hand it to the C fast path so the next repeat never
                # surfaces to Python
                if (udp_sem and self._fastpath is not None
                        and self._fastpath_active()):
                    claimed = self.answer_cache.take_push(key, epoch)
                    if claimed is not None:
                        qname_low = data[12:q_end - 4].lower()
                        ckey = _fastpath_key_parts(
                            bool(rd_flag), edns, payload, qtype_val, 1,
                            qname_low)
                        try:
                            _fastio.fastpath_put(
                                self._fastpath, ckey, qtype_val, epoch,
                                [v[0] for v in claimed[0]],
                                int(self.answer_cache.expiry_s * 1000),
                                qname_low)
                        except (TypeError, ValueError, MemoryError) as e:
                            self.log.debug("fastpath push skipped: %s",
                                           e)
            except Exception:
                # response already sent: never fall through to the
                # generic path (it would answer a second time)
                self.log.exception("raw lane post-send bookkeeping failed")
            return True

        # Mutation-time precompiled probe (the lane edition of
        # _serve_compiled): a dict probe + RD patch + the same id/case
        # splice as the hit path above, instead of the inline resolve
        # below.  Declines to the resolve on truncation overflow.
        comp = self.answer_cache.get_compiled(qtype_val, name, epoch)
        if comp is not None:
            (w0, w1, ans, add), rotatable, tag, negative = comp
            cw = w1 if edns else w0
            if not (udp_sem and len(cw) > payload):
                if rd_flag:
                    cw = patch_answer_wire(cw, rd=True)
                wire = (data[:2] + cw[2:12] + data[12:q_end]
                        + cw[q_end:])
                send(wire)
                try:
                    self._precompile_serve_child.inc()
                    self._lane_finish(data, src, protocol, start, wire,
                                      wire[3] & 0x0F, edns, ans, add,
                                      qtype=qtype_val, cached=True)
                    self.answer_cache.put(
                        key, epoch, (cw, ans, add), rotatable=rotatable,
                        tag=tag, negative=negative,
                        qkey=(qtype_val, name))
                except Exception:
                    # response already sent: never fall through to the
                    # generic path (it would answer a second time)
                    self.log.exception(
                        "raw lane post-send bookkeeping failed")
                return True

        # -- resolution --
        body = b""
        ancount = 0
        ans = []
        if qtype_val == 1:
            # mirrors Resolver.resolve ordering exactly
            rcode = 0
            node = None
            if not name.endswith(dd_suffix):
                rcode = Rcode.REFUSED  # not within dns domain suffix
            else:
                stripped = name[:-len(dd_suffix)]
                dd = self.resolver.dns_domain
                if (stripped == dd or stripped.endswith(dd_suffix)
                        or stripped == self._lane_dcsuff
                        or stripped.endswith("." + self._lane_dcsuff)):
                    rcode = Rcode.REFUSED  # doubled-up dns domain suffix
                elif not cache.is_ready():
                    self.log.error("no coordination-store session")
                    rcode = Rcode.SERVFAIL
                else:
                    node = cache.lookup(name)
                    if node is None:
                        if (self.resolver.recursion is not None
                                and rd_flag):
                            return False  # recursion handoff: generic
                        rcode = Rcode.REFUSED

            if rcode == 0 and node is not None:
                rec = node.rec
                if type(rec) is tuple:
                    # compact host-like (store/names.py): address and
                    # int TTLs by invariant, canonicality still checked
                    if rec[0] not in _LANE_HOST_TYPES:
                        return False
                    addr = rec[1]
                    ttl = _rec_ttl(rec)
                else:
                    rt = rec.get("type") if type(rec) is dict else None
                    if rt not in _LANE_HOST_TYPES:
                        return False   # service/database/invalid record
                    sub = rec.get(rt)
                    if type(sub) is not dict:
                        return False
                    addr = sub.get("address")
                    if type(addr) is not str:
                        return False
                    ttl = _lane_ttl(rec, sub)
                    if ttl is None:
                        return False   # store garbage: generic path
                try:
                    packed = _socket.inet_aton(addr)
                except (OSError, TypeError):
                    return False       # generic path SERVFAILs
                if _socket.inet_ntoa(packed) != addr:
                    return False       # non-canonical dotted quad
                body = (b"\xc0\x0c\x00\x01\x00\x01"
                        + struct.pack(">IH", ttl & 0xFFFFFFFF, 4)
                        + packed)
                ancount = 1
                # same string _summarize(ARecord) renders, through the
                # one redaction helper, without the record-object round
                # trip
                ans = [f"{strip_suffix(dd_suffix, name)} A {addr}"]
        else:
            # PTR: mirrors Resolver.resolve_ptr exactly — note there is
            # NO dnsDomain suffix policy on the reverse tree
            # (lib/server.js:67-134)
            rcode = 0
            ip = None
            parts = name.split(".")
            if len(parts) >= 2 and parts[-1] == "arpa" \
                    and parts[-2] == "ip6":
                # IPv6 reverse: strict canonical nibble parse (the
                # reverse map is keyed by canonical address strings);
                # malformed ip6.arpa names miss below
                ip = ip_from_reverse_name(name)
                if ip is None:
                    rcode = Rcode.REFUSED
            elif len(parts) < 2 or parts[-1] != "arpa" \
                    or parts[-2] != "in-addr":
                rcode = Rcode.REFUSED  # not an ip reverse name
            if rcode == 0 and not cache.is_ready():
                self.log.error("no coordination-store session")
                rcode = Rcode.SERVFAIL
            elif rcode == 0:
                if ip is None:
                    # no octet validation: an invalid address simply
                    # misses (comment at lib/server.js:79-83)
                    ip = ".".join(reversed(parts[:-2]))
                node = cache.reverse_lookup(ip)
                if node is None:
                    if self.resolver.recursion is not None and rd_flag:
                        return False   # recursion handoff: generic path
                    rcode = Rcode.REFUSED
                else:
                    rec = node.rec
                    if type(rec) is tuple:
                        ttl = _rec_ttl(rec)
                    else:
                        record = rec if type(rec) is dict else {}
                        rt = record.get("type")
                        sub = record.get(rt) if type(rt) is str else None
                        ttl = _lane_ttl(record, sub)
                        if ttl is None:
                            return False   # store garbage: generic path
                    target = node.domain
                    if target.endswith(".arpa"):
                        # the generic encoder could compress the target
                        # against the reverse qname; keep parity by
                        # declining the (absurd) overlap case
                        return False
                    # the one real name encoder enforces the label and
                    # 255-byte total bounds the generic path would
                    # SERVFAIL on; unencodable targets decline
                    tw = self._qname_wire(target)
                    if tw is None:
                        return False
                    body = (b"\xc0\x0c\x00\x0c\x00\x01"
                            + struct.pack(">IH", ttl & 0xFFFFFFFF,
                                          len(tw)) + tw)
                    ancount = 1
                    # the dict _summarize renders for PTR records,
                    # without the record-object round trip
                    ans = [{"type": "PTR", "name": name, "ttl": ttl,
                            "target": target}]

        flags_out = 0x8400 | (0x0100 if rd_flag else 0) | rcode
        wire = (data[:2]
                + struct.pack(">HHHHH", flags_out, 1, ancount, 0,
                              1 if edns else 0)
                + data[12:q_end] + body
                + (_OPT_ECHO_WIRE if edns else b""))
        if udp_sem and len(wire) > payload:
            # a long reverse qname + long target can exceed the UDP
            # ceiling; the generic path owns truncation semantics
            return False
        send(wire)
        try:
            self._lane_finish(data, src, protocol, start, wire, rcode,
                              edns, ans, [], qtype=qtype_val)
            if rcode != Rcode.SERVFAIL:
                # cache entries carry a lowercased question so hits can
                # splice in each requester's own case (generic hits do
                # the same via QueryCtx._echo_question_case).  The
                # native push happens at the entry's first hit above
                # (promote-on-first-hit), never on this cold path.
                q_sec = data[12:q_end]
                q_low = q_sec.lower()
                cache_wire = (wire if q_sec == q_low
                              else wire[:12] + q_low + wire[q_end:])
                # lane answers (hit, miss-REFUSED, suffix-REFUSED) all
                # depend on exactly this name; the qname doubles as the
                # dependency tag.  qkey carries the question identity as
                # re-render evidence — without it, churn on a name served
                # only by this lane would never reach the precompiler
                # (or the propagation tracer's render/install stages)
                self.answer_cache.put(
                    key, epoch, (cache_wire, ans, []), rotatable=False,
                    tag=name, qkey=(qtype_val, name))
        except Exception:
            # response already sent: never fall through to the generic
            # path (it would answer a second time)
            self.log.exception("raw lane post-send bookkeeping failed")
        return True

    def _lane_finish(self, data, src, protocol: str, start: float,
                     wire: bytes, rcode: int, edns: bool, ans, add,
                     qtype: int = 1, cached: bool = False) -> None:
        """Metrics + the slow-query warn for a lane-handled query
        (the lane equivalent of _on_after with queryLog off)."""
        lat_s = time.monotonic() - start
        ch = self._children_for(qtype)
        ch[0].inc()
        ch[1].observe(lat_s)
        ch[2].observe(len(wire))
        lat_ms = lat_s * 1000.0
        if lat_ms > SLOW_QUERY_MS:
            if self.recorder is not None:
                self.recorder.record(
                    "slow-query", trace=None, name="(raw-lane)",
                    qtype=Type.name(qtype), rcode=Rcode.name(rcode),
                    latency_ms=round(lat_ms, 3), stages={})
            log_event(self.log, logging.WARNING, "DNS query",
                      req_id=(data[0] << 8) | data[1], client=src[0],
                      port=f"{src[1]}/{protocol}", edns=edns,
                      cached=cached, rcode=Rcode.name(rcode),
                      answers=ans, additional=add, latency=lat_ms,
                      timers={})

    def _fold_engine_counters(self) -> None:
        # scrapes run on ThreadingHTTPServer threads: fold under the
        # shared lock or two concurrent scrapes double-count the delta
        with self._fp_fold_lock:
            delta = self.engine.tcp_cap_refusals - self._cap_folded
            if delta > 0:
                self._cap_refusal_child.inc(delta)
                self._cap_folded += delta
            snap = self.engine.tcp_stats.snapshot()
            folded = self._tcp_stats_folded
            for field, child in self._tcp_stat_children.items():
                d = snap[field] - folded.get(field, 0)
                if d > 0:
                    child.inc(d)
                    folded[field] = snap[field]
            if self._rrl is not None:
                rfolded = self._rrl_folded
                for field, child in self._rrl_children.items():
                    val = getattr(self._rrl, field)
                    d = val - rfolded.get(field, 0)
                    if d > 0:
                        child.inc(d)
                        rfolded[field] = val

    def _fold_fastpath_metrics(self) -> None:
        """Fold the C fast path's monotonic counters into the Prometheus
        collectors (registered as a pre-scrape hook).  Deltas are taken
        against the last fold under a lock — concurrent scrapes must not
        double-count."""
        with self._fp_fold_lock:
            # Snapshot inside the lock: with it outside, two concurrent
            # scrapes could fold in order new-then-old, regressing the
            # delta baseline and double-counting on the next fold.
            stats = _fastio.fastpath_stats(self._fastpath)
            self._fp_last_stats = stats   # shared with residency gauges
            last = self._fp_folded
            hits_delta = stats["hits"] - last.get("hits", 0)
            if hits_delta > 0:
                self._cache_hit_child.inc(hits_delta)
            last["hits"] = stats["hits"]
            zone_delta = stats.get("zone_hits", 0) - last.get("zone_hits", 0)
            if zone_delta > 0:
                self._zone_serve_child.inc(zone_delta)
            last["zone_hits"] = stats.get("zone_hits", 0)
            self._fp_inval_total = stats.get("invalidations", 0)
            for qtype, s in stats["per_qtype"].items():
                children = self._children_for(qtype)
                prev = last.get(qtype)
                count_delta = s["count"] - (prev["count"] if prev else 0)
                if count_delta > 0:
                    children[0].inc(count_delta)
                    children[1].merge(
                        [c - (prev["lat_cells"][i] if prev else 0)
                         for i, c in enumerate(s["lat_cells"])],
                        s["lat_sum"] - (prev["lat_sum"] if prev else 0.0))
                    children[2].merge(
                        [c - (prev["size_cells"][i] if prev else 0)
                         for i, c in enumerate(s["size_cells"])],
                        s["size_sum"] - (prev["size_sum"] if prev else 0.0))
                last[qtype] = s

    def _children_for(self, qtype: int):
        """Pre-resolved (counter, latency, size) metric handles for a
        qtype — label-sort once, not per query; shared by the after-hook
        and the fast-path fold."""
        children = self._metric_children.get(qtype)
        if children is None:
            # 0xFFFF is the C stats catch-all past its per-qtype slots
            labels = {"type": "other" if qtype == 0xFFFF
                      else Type.name(qtype)}
            children = (self.request_counter.labelled(labels),
                        self.latency_histogram.labelled(labels),
                        self.size_histogram.labelled(labels))
            self._metric_children[qtype] = children
        return children

    def _fastpath_active(self) -> bool:
        """The C path bypasses Python entirely, so it must stand down
        whenever every query has to surface: a probe consumer attached,
        per-query logging on WITHOUT the native log ring (with the
        ring armed, the C path produces the log lines itself), or
        response rate limiting actively shedding a flood (the limiter
        judges per-prefix in Python; serving cache hits in C would
        answer the flood before RRL could see it)."""
        return (not self.p_req_start.enabled
                and not self.p_req_done.enabled
                and (not self.query_log or self._log_ring)
                and (self._rrl is None or not self._rrl.hot()))

    # -- native query-log ring plumbing --

    def _find_json_handlers(self) -> list:
        """StreamHandlers with a JsonFormatter reachable from this
        server's logger (walking propagation like logging does) — the
        sinks the ring's pre-formatted lines are written to."""
        handlers = []
        lg: Optional[logging.Logger] = self.log
        while lg is not None:
            for h in lg.handlers:
                if (isinstance(h, logging.StreamHandler)
                        and isinstance(h.formatter, JsonFormatter)
                        and h.level <= logging.INFO):
                    handlers.append(h)
            if not lg.propagate:
                break
            lg = lg.parent
        return handlers

    def _native_log_prefix(self) -> bytes:
        """Constant head of every native log line, up to and including
        ``"time": "`` — rendered once from the logger's identity, so
        ring lines carry the same envelope as JsonFormatter's."""
        fmt = self._log_json_handlers[0].formatter
        head = {"name": fmt.name, "hostname": fmt.hostname,
                "pid": _os.getpid(), "level": 30,
                "component": self.log.name, "msg": "DNS query"}
        return (_json.dumps(head)[:-1] + ', "time": "').encode()

    @staticmethod
    def _log_frag(ctx: dict, rcode: int, ans, add) -> Optional[bytes]:
        """Pre-rendered middle of a log line (the answer-dependent
        fields) for one entry variant; None when it cannot be rendered
        or would exceed the native bound (the entry then declines to
        Python under logging, which is always correct)."""
        d = dict(ctx)
        d["rcode"] = Rcode.name(rcode)
        d["answers"] = ans
        d["additional"] = add
        try:
            frag = _json.dumps(d, default=str)[1:-1].encode()
        except (TypeError, ValueError):
            return None
        return frag if 0 < len(frag) <= 4096 else None

    def _drain_native_log(self) -> None:
        """Write the ring's accumulated complete lines to the JSON log
        stream(s).  Called from the UDP drain loop (amortized over each
        batch) and from a periodic flusher covering the TCP/balancer
        lanes and idle tails."""
        try:
            block = _fastio.fastpath_log_drain(self._fastpath)
        except (TypeError, ValueError):
            return
        if not block:
            return
        text = None
        for h in self._log_json_handlers:
            try:
                h.acquire()
                try:
                    buf = getattr(h.stream, "buffer", None)
                    # bytes straight through ONLY when the text layer
                    # would have produced the same bytes: UTF-8-family
                    # encoding and no newline translation — otherwise
                    # ring lines and formatter lines would mix
                    # encodings/line-endings in one file
                    enc = (getattr(h.stream, "encoding", "") or "") \
                        .lower().replace("-", "")
                    nl = getattr(h.stream, "newlines", None)
                    if (buf is not None
                            and enc in ("utf8", "ascii", "usascii")
                            and nl in (None, "\n")):
                        # (flush the text layer first so lines the
                        # Python formatter wrote stay ordered)
                        h.stream.flush()
                        buf.write(block)
                        buf.flush()
                    else:
                        if text is None:
                            text = block.decode("utf-8", "replace")
                        h.stream.write(text)
                        h.flush()
                finally:
                    h.release()
            except Exception:
                pass   # a dead log sink must never take down serving

    async def _log_flush_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(0.1)
                self._drain_native_log()
        except asyncio.CancelledError:
            self._drain_native_log()
            raise

    # -- after hook: metrics + query log (lib/server.js:509-591) --

    def _on_after(self, query: QueryCtx) -> None:
        query.stamp("log-after")
        lat_ms = query.latency_ms()
        if self.p_req_done.enabled:
            self.p_req_done.fire(lambda: {
                "trace": query.trace_id,
                "id": query.request.id, "name": query.name(),
                "type": query.qtype_name(),
                "rcode": Rcode.name(query.rcode()),
                "latency_ms": round(lat_ms, 3), "bytes": query.bytes_sent,
                "stages": {k: round(v, 3)
                           for k, v in query.times.items()},
            })
        level = logging.WARNING if lat_ms > SLOW_QUERY_MS else logging.INFO
        if lat_ms > SLOW_QUERY_MS and self.recorder is not None:
            self.recorder.record(
                "slow-query", trace=query.trace_id, name=query.name(),
                qtype=query.qtype_name(), rcode=Rcode.name(query.rcode()),
                latency_ms=round(lat_ms, 3),
                stages={k: round(v, 3) for k, v in query.times.items()})

        children = self._children_for(query.qtype())
        children[0].inc()
        children[1].observe(lat_ms / 1000.0)
        children[2].observe(query.bytes_sent)
        for stage, ms in query.times.items():
            child = self._stage_children.get(stage)
            if child is None:
                child = self._stage_children[stage] = \
                    self.stage_histogram.labelled({"stage": stage})
            child.observe(ms / 1000.0)

        if not self.query_log and lat_ms <= SLOW_QUERY_MS:
            return
        if query.cached_summary is not None:
            ans, add = query.cached_summary
        else:
            ans = [self._summarize(r) for r in query.response.answers]
            add = [self._summarize(r) for r in query.response.additionals
                   if not isinstance(r, OPTRecord)]
        log_event(
            self.log, level, "DNS query",
            # request envelope built here, not per-query in _on_query:
            # most queries never log (queryLog off / fast), so the dict
            # work happens only on the slow/logged path
            trace=query.trace_id,
            req_id=query.request.id,
            client=query.src[0],
            port=f"{query.src[1]}/{query.protocol}",
            edns=query.request.edns is not None,
            **query.log_ctx,
            rcode=Rcode.name(query.rcode()),
            answers=ans,
            additional=add,
            latency=lat_ms,
            timers=query.times,
        )

    def _summarize(self, rec) -> object:
        if isinstance(rec, SRVRecord):
            return (f"SRV {strip_suffix('.' + self.dns_domain, rec.target)}"
                    f":{rec.port}")
        if isinstance(rec, ARecord):
            return (f"{strip_suffix('.' + self.dns_domain, rec.name)} "
                    f"A {rec.address}")
        d = {"type": Type.name(rec.rtype), "name": rec.name, "ttl": rec.ttl}
        if hasattr(rec, "target"):
            d["target"] = rec.target
        return d

    # -- lifecycle (lib/server.js:609-657) --

    #: ephemeral pair-bind redraws before giving up; each failure means
    #: the kernel-chosen UDP port was taken on TCP, so consecutive
    #: failures are near-independent draws from the ephemeral range
    _PAIR_BIND_ATTEMPTS = 16

    async def start(self) -> None:
        if self._precompiler is not None:
            # compile the already-mirrored names (mirrors built before
            # this server subscribed to invalidation events); mutation
            # events keep the table fresh from here on
            self._precompiler.seed_mirror()
        self._zone_fill()
        if self.balancer_socket:
            await self.engine.listen_balancer(self.balancer_socket)
        # UDP and TCP must share one port number (the reference serves
        # both on the same port, lib/server.js:643-653).  With port=0
        # the kernel picks the UDP port and any unrelated socket may
        # already hold that number on TCP — so the pair bind is a retry
        # loop: release the UDP draw and redraw instead of failing
        # (the observed CI flake: EADDRINUSE on the UDP-chosen port).
        for attempt in range(self._PAIR_BIND_ATTEMPTS):
            # announce only once the PAIR is secured: harnesses watch
            # the "service started" lines for the port, and a line
            # printed for a draw that is then released and redrawn
            # advertises a dead port (observed as a CI dnsblast
            # connection-refused failure)
            try:
                udp_port = await self.engine.listen_udp(
                    self.host, self.port, announce=False,
                    reuse_port=self.reuse_port)
            except OSError:
                # a UDP bind failure (fixed port taken) must release
                # the balancer listener opened above, like the TCP path
                await self.engine.close()
                raise
            try:
                self.tcp_port = await self.engine.listen_tcp(
                    self.host, self.port if self.port else udp_port,
                    announce=False, reuse_port=self.reuse_port)
            except OSError as e:
                # the failed draw must be released even when re-raising:
                # callers treat start() as atomic and won't stop() a
                # server that never started
                self.engine.close_udp_listener(udp_port)
                # errno is None when asyncio aggregates several bind
                # failures (multi-address hosts) into one OSError — a
                # colliding draw must redraw in that shape too
                if (self.port == 0
                        and e.errno in (_errno.EADDRINUSE, None)
                        and attempt < self._PAIR_BIND_ATTEMPTS - 1):
                    continue
                # failed for good: release the balancer listener opened
                # above so the raise leaves no socket behind
                await self.engine.close()
                raise
            self.udp_port = udp_port
            if self.announce:
                self.engine.announce_udp(self.host, udp_port)
                self.engine.announce_tcp(self.host, self.tcp_port)
            break
        if self._log_ring and self._log_flush_task is None:
            # periodic drain for the lanes without a C drain loop of
            # their own (TCP/balancer serves) and for idle tails
            self._log_flush_task = asyncio.get_running_loop().create_task(
                self._log_flush_loop())
        if self._policy is not None and self._policy_task is None:
            self._policy_task = asyncio.get_running_loop().create_task(
                self._policy_tick_loop())
        if self._verify is not None:
            self._verify.start(asyncio.get_running_loop())

    async def stop(self) -> None:
        if self._verify is not None:
            await self._verify.stop()
        if self._policy_task is not None:
            self._policy_task.cancel()
            try:
                await self._policy_task
            except asyncio.CancelledError:
                pass
            self._policy_task = None
        if self._log_flush_task is not None:
            self._log_flush_task.cancel()
            try:
                await self._log_flush_task
            except asyncio.CancelledError:
                pass
            self._log_flush_task = None
        if self._log_ring:
            self._drain_native_log()
        await self.engine.close()


def create_server(**kwargs) -> BinderServer:
    return BinderServer(**kwargs)
