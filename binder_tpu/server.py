"""The binder server: transport engine + resolution + observability.

Port of the reference's ``createServer`` wiring (``lib/server.js:435-660``):
attaches the resolution engine to the transport engine's ``query`` hook,
and metrics + structured query logging to the ``after`` hook.  ``start()``
brings up UDP + TCP listeners and, when configured, the balancer UNIX
socket (``lib/server.js:609-653``).
"""
from __future__ import annotations

import logging
from typing import Optional

from binder_tpu.dns.query import QueryCtx
from binder_tpu.dns.server import DnsServer
from binder_tpu.dns.wire import (
    ARecord,
    OPTRecord,
    Rcode,
    SRVRecord,
    Type,
)
from binder_tpu.metrics.collector import (
    DEFAULT_SIZE_BUCKETS,
    MetricsCollector,
)
from binder_tpu.resolver.answer_cache import AnswerCache
from binder_tpu.resolver.engine import Resolver
from binder_tpu.utils.jsonlog import log_event
from binder_tpu.utils.probes import ProbeProvider

METRIC_REQUEST_COUNTER = "binder_requests_completed"
METRIC_LATENCY_HISTOGRAM = "binder_request_latency_seconds"
METRIC_SIZE_HISTOGRAM = "binder_response_size_bytes"

SLOW_QUERY_MS = 1000.0  # log at warn above this (lib/server.js:511-514)


def strip_suffix(suffix: str, s: str) -> str:
    """Log redaction of the (long, constant) DNS domain
    (lib/server.js:60-65)."""
    if s.endswith(suffix):
        return s[:len(s) - len(suffix)] + "..."
    return s


class BinderServer:
    def __init__(self, *, zk_cache, dns_domain: str,
                 datacenter_name: str = "",
                 recursion=None,
                 log: Optional[logging.Logger] = None,
                 collector: Optional[MetricsCollector] = None,
                 name: str = "binder",
                 host: str = "127.0.0.1", port: int = 53,
                 balancer_socket: Optional[str] = None,
                 query_log: bool = True,
                 cache_size: int = 10000,
                 cache_expiry_ms: int = 60000,
                 probes: Optional[ProbeProvider] = None) -> None:
        self.log = log or logging.getLogger("binder.server")
        self.host = host
        self.port = port
        self.dns_domain = dns_domain
        self.balancer_socket = balancer_socket
        self.collector = collector or MetricsCollector()
        # per-query logging can be disabled for high-qps deployments;
        # slow queries (>1s) are logged regardless
        self.query_log = query_log
        # encoded-answer cache (the reference's -s/-a flags, main.js:34-38)
        self.zk_cache = zk_cache
        self.answer_cache = AnswerCache(size=cache_size,
                                        expiry_ms=cache_expiry_ms)
        self.cache_hit_counter = self.collector.counter(
            "binder_answer_cache_hits", "encoded-answer cache hits")
        self._cache_hit_child = self.cache_hit_counter.labelled()

        self.request_counter = self.collector.counter(
            METRIC_REQUEST_COUNTER, "count of Binder requests completed")
        self.latency_histogram = self.collector.histogram(
            METRIC_LATENCY_HISTOGRAM,
            "total time to process Binder requests")
        self.size_histogram = self.collector.histogram(
            METRIC_SIZE_HISTOGRAM, "size in bytes of Binder responses",
            buckets=DEFAULT_SIZE_BUCKETS)
        # per-qtype pre-resolved metric handles (label-sort once, not
        # per query); key is the numeric qtype
        self._metric_children: dict = {}

        # USDT analog: provider 'binder', probes op-req-start/op-req-done
        # fired with the query context (lib/server.js:24-29,472-474,516-518)
        self.probes = probes or ProbeProvider("binder")
        self.p_req_start = self.probes.probe("op-req-start")
        self.p_req_done = self.probes.probe("op-req-done")

        self.resolver = Resolver(zk_cache, dns_domain=dns_domain,
                                 datacenter_name=datacenter_name,
                                 recursion=recursion, log=self.log)
        self.engine = DnsServer(log=self.log, name=name)
        self.engine.on_query = self._on_query
        self.engine.on_after = self._on_after

        # actual bound ports (for tests / ephemeral binds)
        self.udp_port: Optional[int] = None
        self.tcp_port: Optional[int] = None

    # -- query hook (lib/server.js:471-507); sync, may return an awaitable
    # for the recursion path (see DnsServer._dispatch) --

    def _on_query(self, query: QueryCtx):
        if self.p_req_start.enabled:   # skip closure alloc when off
            self.p_req_start.fire(lambda: {
                "id": query.request.id, "name": query.name(),
                "type": query.qtype_name(), "client": query.src[0],
                "protocol": query.protocol,
            })
        # Answer-cache fast path.  The key is built from the decoded
        # fields the response actually depends on — transport semantics
        # (truncation), RD (drives the recursion-vs-REFUSED split on
        # misses), question, EDNS presence and payload ceiling — NOT the
        # raw wire: wire bytes vary with per-packet EDNS options (DNS
        # cookies, padding) and ignored padding sections, which would
        # mint one key per packet and evict the real entries.
        key = None
        req = query.request
        if len(req.questions) == 1 and req.opcode == 0:
            q0 = req.questions[0]
            key = (query.udp_semantics, req.rd, q0.qtype, q0.qclass,
                   q0.name, req.edns is not None, req.max_udp_payload())
            cached = self.answer_cache.get(key, self.zk_cache.gen)
            if cached is not None:
                wire, ans, add = cached
                self._cache_hit_child.inc()
                query.response.rcode = wire[3] & 0x0F  # for metrics/logs
                query.log_ctx["cached"] = True
                query.cached_summary = (ans, add)
                query.respond_raw(wire)
                return None

        pending = self.resolver.handle(query)

        if (pending is None and key is not None and query.responded
                and query.wire is not None
                and query.rcode() != Rcode.SERVFAIL):
            ans = [self._summarize(r) for r in query.response.answers]
            add = [self._summarize(r) for r in query.response.additionals
                   if not isinstance(r, OPTRecord)]
            # reused by _on_after for this query's own log line too —
            # summaries are built exactly once per resolve
            query.cached_summary = (ans, add)
            self.answer_cache.put(
                key, self.zk_cache.gen, (query.wire, ans, add),
                rotatable=len(query.response.answers) > 1)
        return pending

    # -- after hook: metrics + query log (lib/server.js:509-591) --

    def _on_after(self, query: QueryCtx) -> None:
        query.stamp("log-after")
        lat_ms = query.latency_ms()
        if self.p_req_done.enabled:
            self.p_req_done.fire(lambda: {
                "id": query.request.id, "name": query.name(),
                "type": query.qtype_name(),
                "rcode": Rcode.name(query.rcode()),
                "latency_ms": round(lat_ms, 3), "bytes": query.bytes_sent,
            })
        level = logging.WARNING if lat_ms > SLOW_QUERY_MS else logging.INFO

        children = self._metric_children.get(query.qtype())
        if children is None:
            labels = {"type": query.qtype_name()}
            children = (self.request_counter.labelled(labels),
                        self.latency_histogram.labelled(labels),
                        self.size_histogram.labelled(labels))
            self._metric_children[query.qtype()] = children
        children[0].inc()
        children[1].observe(lat_ms / 1000.0)
        children[2].observe(query.bytes_sent)

        if not self.query_log and lat_ms <= SLOW_QUERY_MS:
            return
        if query.cached_summary is not None:
            ans, add = query.cached_summary
        else:
            ans = [self._summarize(r) for r in query.response.answers]
            add = [self._summarize(r) for r in query.response.additionals
                   if not isinstance(r, OPTRecord)]
        log_event(
            self.log, level, "DNS query",
            # request envelope built here, not per-query in _on_query:
            # most queries never log (queryLog off / fast), so the dict
            # work happens only on the slow/logged path
            req_id=query.request.id,
            client=query.src[0],
            port=f"{query.src[1]}/{query.protocol}",
            edns=query.request.edns is not None,
            **query.log_ctx,
            rcode=Rcode.name(query.rcode()),
            answers=ans,
            additional=add,
            latency=lat_ms,
            timers=query.times,
        )

    def _summarize(self, rec) -> object:
        if isinstance(rec, SRVRecord):
            return (f"SRV {strip_suffix('.' + self.dns_domain, rec.target)}"
                    f":{rec.port}")
        if isinstance(rec, ARecord):
            return (f"{strip_suffix('.' + self.dns_domain, rec.name)} "
                    f"A {rec.address}")
        d = {"type": Type.name(rec.rtype), "name": rec.name, "ttl": rec.ttl}
        if hasattr(rec, "target"):
            d["target"] = rec.target
        return d

    # -- lifecycle (lib/server.js:609-657) --

    async def start(self) -> None:
        if self.balancer_socket:
            await self.engine.listen_balancer(self.balancer_socket)
        self.udp_port = await self.engine.listen_udp(self.host, self.port)
        self.tcp_port = await self.engine.listen_tcp(
            self.host, self.port if self.port else self.udp_port)

    async def stop(self) -> None:
        await self.engine.close()


def create_server(**kwargs) -> BinderServer:
    return BinderServer(**kwargs)
