"""Overload admission control: bounded in-flight table + client buckets.

Two distinct overload shapes, two levers:

- **In-flight overflow** (oldest-shed).  Queries that go async
  (recursion forwards, parked handlers) sit in the engine's in-flight
  table.  Under an upstream brown-out that table grows without bound —
  every entry holds a client still waiting, and the oldest entries are
  the ones least likely to ever complete usefully (their clients have
  long retried).  When the table exceeds ``maxInflight``, the OLDEST
  in-flight query is shed: it gets an immediate well-formed REFUSED
  (clients fail over to their next nameserver — the engine's standing
  rcode policy) and its task is cancelled, so the table bounds both
  memory and upstream fan-out.  A hang is never the failure mode.

- **Recursion-triggering floods** (per-client token buckets).  A
  single client hammering cold RD names converts cheap local misses
  into expensive cross-DC forwards — the NXNSAttack amplification
  shape (PAPERS.md).  Each client IP gets a token bucket
  (``recursionRate``/s, burst ``recursionBurst``); an empty bucket
  REFUSES the forward *before* any upstream work.  Mirror-served
  queries are never charged — only the queries that would fan out.

Both shed paths count into ``binder_shed_total{reason=...}`` (series
materialized at 0 so rate() works from the first scrape), emit
rate-limited ``query-shed`` flight-recorder events, and surface in
``/status`` under ``policy.admission``.
"""
from __future__ import annotations

import logging
import time
from typing import Dict, Optional, Tuple

DEFAULT_MAX_INFLIGHT = 512
DEFAULT_RECURSION_RATE = 50.0     # tokens/second per client
DEFAULT_RECURSION_BURST = 100.0
#: client buckets tracked at once (LRU): bounds memory under address
#: spoofing; an evicted client simply starts with a full bucket
MAX_CLIENTS = 4096

SHED_REASONS = ("inflight-overflow", "recursion-ratelimit",
                "response-ratelimit")


class AdmissionControl:
    #: shed flight-recorder events are rate-limited to one per window
    SHED_EVENT_WINDOW_S = 1.0

    def __init__(self, *, max_inflight: int = DEFAULT_MAX_INFLIGHT,
                 recursion_rate: float = DEFAULT_RECURSION_RATE,
                 recursion_burst: float = DEFAULT_RECURSION_BURST,
                 collector=None, recorder=None,
                 log: Optional[logging.Logger] = None) -> None:
        self.max_inflight = int(max_inflight)
        self.recursion_rate = float(recursion_rate)
        self.recursion_burst = float(recursion_burst)
        self.recorder = recorder
        self.log = log or logging.getLogger("binder.admission")
        # client ip -> (tokens, last_refill_mono); insertion-ordered LRU
        self._buckets: Dict[str, Tuple[float, float]] = {}
        self.shed_counts = {reason: 0 for reason in SHED_REASONS}
        self._shed_children: Dict[str, object] = {}
        self._shed_event_last = 0.0
        if collector is not None:
            counter = collector.counter(
                "binder_shed_total",
                "queries shed by overload admission control, by reason")
            for reason in SHED_REASONS:
                child = counter.labelled({"reason": reason})
                child.inc(0)    # series exists from scrape 1
                self._shed_children[reason] = child

    # -- shared accounting --

    def _note_shed(self, reason: str, **detail) -> None:
        self.shed_counts[reason] += 1
        child = self._shed_children.get(reason)
        if child is not None:
            child.inc()
        now = time.monotonic()
        if (self.recorder is not None
                and now - self._shed_event_last >= self.SHED_EVENT_WINDOW_S):
            self._shed_event_last = now
            self.recorder.record("query-shed", reason=reason, **detail)

    # -- in-flight overflow (wired into DnsServer._dispatch) --

    def shed_overflow(self, engine) -> None:
        """Shed oldest in-flight queries until the table is back at
        the cap.  Called by the engine right after it admits a new
        async query; each shed query gets an immediate REFUSED and its
        driver task (if any) is cancelled."""
        from binder_tpu.dns.wire import Rcode   # local: no import cycle
        inflight = engine.inflight
        while len(inflight) > self.max_inflight:
            qid, query = next(iter(inflight.items()))
            del inflight[qid]
            task = engine.inflight_tasks.pop(qid, None)
            if not query.responded:
                query.reset_sections()
                query.set_error(Rcode.REFUSED)
                query.log_ctx["reason"] = "shed: in-flight overflow"
                try:
                    query.respond()
                except OSError:
                    pass
            self._note_shed("inflight-overflow",
                            trace=query.trace_id, name=query.name(),
                            age_ms=round(query.latency_ms(), 1),
                            inflight=len(inflight))
            # metrics/log for the shed query run NOW; the guard in
            # engine._after keeps the cancelled task's own completion
            # from double-counting it
            engine._after(query)
            if task is not None:
                task.cancel()

    # -- recursion-triggering floods (wired into Resolver._finish) --

    def allow_recursion(self, client_ip: str) -> bool:
        """Charge one token against *client_ip*'s bucket; False means
        the forward must be refused (the caller answers REFUSED)."""
        now = time.monotonic()
        entry = self._buckets.pop(client_ip, None)
        if entry is None:
            if len(self._buckets) >= MAX_CLIENTS:
                self._buckets.pop(next(iter(self._buckets)))
            tokens = self.recursion_burst
        else:
            tokens, last = entry
            tokens = min(self.recursion_burst,
                         tokens + (now - last) * self.recursion_rate)
        if tokens < 1.0:
            self._buckets[client_ip] = (tokens, now)
            self._note_shed("recursion-ratelimit", client=client_ip)
            return False
        self._buckets[client_ip] = (tokens - 1.0, now)
        return True

    # -- introspection (status.py `policy.admission`) --

    def introspect(self, engine=None) -> dict:
        return {
            "max_inflight": self.max_inflight,
            "inflight": (len(engine.inflight) if engine is not None
                         else 0),
            "recursion_rate": self.recursion_rate,
            "recursion_burst": self.recursion_burst,
            "clients_tracked": len(self._buckets),
            "shed": dict(self.shed_counts),
        }
