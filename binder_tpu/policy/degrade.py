"""Stale-serve degradation policy: what binder does once the store dies.

PR 2 made the dominant silent failure *visible* — a binder whose ZK
session is gone keeps serving an aging mirror with every query looking
fine.  This module is the *policy* for that state, RFC 8767-style:

- while the session is up: **fresh** — serve normally;
- session lost, mirror age within ``maxStalenessSeconds``:
  **stale-serving** — keep answering from the mirror, with every
  record's TTL clamped to ``staleTtlClampSeconds`` (RFC 8767 §5
  recommends a low TTL so clients re-ask and notice recovery fast);
- past the cap: **stale-exhausted** — answers are *withheld* per
  ``exhaustedAction``: ``servfail`` (default; clients fail over per
  the engine's rcode policy) or ``nodata`` (NOERROR + SOA, negative-
  cacheable).  Data older than the cap is never served, from any lane.

The cap covers the *cached* lanes too: every transition bumps the
mirror epoch (``MirrorCache.invalidate_all``), so the Python answer
cache, the compiled table, the native C caches, and the balancer all
drop answers rendered under the previous mode — an answer rendered
fresh can never be served into exhaustion, and clamped-TTL stale
answers never survive recovery.

State is evaluated lazily on the query path (a couple of attribute
reads) and by a 1 s ticker (``BinderServer``) so transitions — and
their ``binder_degraded_state`` metric and ``degraded-transition``
flight-recorder events — fire even on an idle binder.  The whole
state machine derives from the PR 2 session state machine's *measured*
``disconnected_seconds``; nothing here is inferred.
"""
from __future__ import annotations

import logging
import time
from collections import deque
from typing import List, Optional

#: degradation states, in increasing severity; the metric encodes the
#: index (binder_degraded_state: 0 fresh / 1 stale-serving /
#: 2 stale-exhausted — "returns to 0" is the recovery assertion)
STATES = ("fresh", "stale-serving", "stale-exhausted")
STATE_CODES = {s: i for i, s in enumerate(STATES)}

DEFAULT_MAX_STALENESS_S = 300.0
DEFAULT_STALE_TTL_CLAMP_S = 30
EXHAUSTED_ACTIONS = ("servfail", "nodata")


class DegradationPolicy:
    def __init__(self, *, store, zk_cache,
                 max_staleness_s: float = DEFAULT_MAX_STALENESS_S,
                 stale_ttl_clamp_s: int = DEFAULT_STALE_TTL_CLAMP_S,
                 exhausted_action: str = "servfail",
                 collector=None, recorder=None,
                 log: Optional[logging.Logger] = None,
                 history: int = 64) -> None:
        if exhausted_action not in EXHAUSTED_ACTIONS:
            raise ValueError(
                f"exhaustedAction must be one of {EXHAUSTED_ACTIONS}, "
                f"got {exhausted_action!r}")
        self.store = store
        self.zk_cache = zk_cache
        self.max_staleness_s = float(max_staleness_s)
        self.stale_ttl_clamp_s = int(stale_ttl_clamp_s)
        self.exhausted_action = exhausted_action
        self.recorder = recorder
        self.log = log or logging.getLogger("binder.policy")
        self._state = "fresh"
        self._since = time.monotonic()
        self._transitions: deque = deque(maxlen=history)
        self._transition_cbs: List = []
        self.stale_served = 0       # answers served in stale mode
        self.withheld = 0           # answers withheld in exhausted mode
        self._m_stale = self._m_withheld = None
        if collector is not None:
            collector.gauge(
                "binder_degraded_state",
                "degradation state machine (0 fresh, 1 stale-serving, "
                "2 stale-exhausted)"
            ).set_function(lambda: float(STATE_CODES[self.mode()]))
            self._m_stale = collector.counter(
                "binder_stale_served_total",
                "answers served from a stale mirror (TTL-clamped, "
                "within maxStalenessSeconds)").labelled()
            self._m_withheld = collector.counter(
                "binder_stale_withheld_total",
                "answers withheld past maxStalenessSeconds "
                "(exhaustedAction applied)").labelled()
            # series exist from scrape 1: degradation evidence must be
            # rate()-able before the first incident
            self._m_stale.inc(0)
            self._m_withheld.inc(0)

    def on_transition(self, cb) -> None:
        """Subscribe to state edges: cb(old, new).  BinderServer wires
        the epoch bump (cache invalidation) here."""
        self._transition_cbs.append(cb)

    # -- the state machine --

    def _evaluate(self) -> str:
        getter = getattr(self.store, "disconnected_seconds", None)
        if getter is None:
            # store without a session state machine (bare test doubles):
            # is_connected is all there is
            return ("fresh" if self.store.is_connected()
                    else "stale-serving")
        ds = getter()
        if ds is None:
            # never connected: there is no stale data to police — the
            # engine's not-ready SERVFAIL path owns this shape
            return "fresh"
        if ds <= 0.0 and self.store.is_connected():
            return "fresh"
        if ds <= self.max_staleness_s:
            return "stale-serving"
        return "stale-exhausted"

    def mode(self) -> str:
        """Current state, transitioning (and notifying) if the measured
        disconnection age moved the machine.  Cheap enough for the
        query path: two attribute reads and a comparison in the steady
        (fresh) state."""
        new = self._evaluate()
        old = self._state
        if new != old:
            now = time.monotonic()
            self._state = new
            self._since = now
            self._transitions.append({
                "t_mono": now, "t_wall": time.time(),
                "from": old, "to": new,
            })
            if self.recorder is not None:
                self.recorder.record(
                    "degraded-transition", frm=old, to=new,
                    disconnected_seconds=getattr(
                        self.store, "disconnected_seconds",
                        lambda: None)(),
                    max_staleness_seconds=self.max_staleness_s)
            level = (logging.WARNING if new != "fresh" else logging.INFO)
            self.log.log(level, "degradation state %s -> %s "
                         "(maxStalenessSeconds=%g)", old, new,
                         self.max_staleness_s)
            for cb in list(self._transition_cbs):
                try:
                    cb(old, new)
                except Exception:  # noqa: BLE001 — a subscriber bug
                    self.log.exception("degradation transition callback "
                                       "failed")   # must not stop serving
        return self._state

    tick = mode   # the periodic evaluator is the lazy one, by design

    # -- query-path accounting --

    def note_stale_served(self) -> None:
        self.stale_served += 1
        if self._m_stale is not None:
            self._m_stale.inc()

    def note_withheld(self) -> None:
        self.withheld += 1
        if self._m_withheld is not None:
            self._m_withheld.inc()

    def clamp_ttl(self, ttl: int) -> int:
        return min(ttl, self.stale_ttl_clamp_s)

    # -- introspection (status.py `policy.degradation`) --

    def introspect(self) -> dict:
        now = time.monotonic()
        return {
            "state": self.mode(),
            "state_since_seconds": now - self._since,
            "max_staleness_seconds": self.max_staleness_s,
            "stale_ttl_clamp_seconds": self.stale_ttl_clamp_s,
            "exhausted_action": self.exhausted_action,
            "mirror_staleness_seconds":
                self.zk_cache.staleness_seconds(),
            "stale_served": self.stale_served,
            "withheld": self.withheld,
            "transitions": [
                {"t_wall": tr["t_wall"],
                 "age_seconds": now - tr["t_mono"],
                 "from": tr["from"], "to": tr["to"]}
                for tr in self._transitions],
        }
