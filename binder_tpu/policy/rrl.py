"""Response rate limiting (RRL): per-client-prefix slip/drop on UDP.

The admission layer (`admission.py`) bounds the *expensive* work a
client can trigger — recursion forwards, in-flight table growth.  It
deliberately never touches the cheap mirror-served path, which is why
a spoofed-source UDP flood sails straight through it: every spoofed
packet is a fresh "client", every answer is a cache hit, and binder
happily becomes a reflection amplifier while legitimate traffic
starves behind the flood in the socket buffer.

RRL is the classic countermeasure (BIND/NSD ship the same shape): rate
limit *responses* per client network prefix, and for a fraction of
limited traffic send a truncated (TC=1) echo — the "slip" — instead of
silence.  A real client behind a rate-limited prefix retries over TCP
and gets a full answer; a spoofed victim receives a tiny TC packet
(smaller than the query — negative amplification) and nothing else.

Mechanics, mirroring `AdmissionControl`'s house style:

- Token bucket per prefix (``/24`` v4, ``/56`` v6 by default — one
  host of a spoofed 64-bit-IID v6 flood must not mint one bucket per
  packet).  Buckets live in an insertion-ordered LRU capped at
  ``maxBuckets``; an evicted prefix restarts with a full bucket, so
  the table bounds memory under arbitrary source diversity.
- Every ``slipRatio``-th limited response slips (TC echo); the rest
  drop silently.  ``slipRatio=0`` means pure drop, ``1`` slips
  everything.
- Drops count into ``binder_shed_total{reason="response-ratelimit"}``
  through the admission layer's `_note_shed` (same rate-limited
  ``query-shed`` flight event); the limiter additionally keeps its own
  fold-ready plain-int counters (``binder_rrl_*``) and emits a
  rate-limited ``hostile-flood`` flight event when limiting starts.
- ``hot()`` reports "limiting happened recently".  BinderServer
  couples it into the native fastpath gate: while a flood is being
  shed, every packet must surface to Python so the limiter can judge
  it — the C drain loop answers cache hits before RRL could see them.
  Costing the flood window the fastpath is the honest trade; the
  limiter then sheds at its own (cheap, decode-free) ingress.
- Detection under the fastpath: a cache-hit flood answered entirely
  in C would never reach `decide()` to trip ``hot()`` in the first
  place.  The batched UDP reader therefore **duty-cycle samples**
  while the gate is open: every ``FASTPATH_SAMPLE_EVERY``-th
  readiness event drains through Python with ``sample_cost`` set to
  the sampling factor, so each sampled packet charges its prefix what
  the unsampled stream would have.  A flooded prefix overdraws within
  a bucket-burst of sampled traffic → ``hot()`` → gate shut → full
  per-packet judgment until the flood subsides.

The limiter judges the packet *before* decode on the UDP lane, so
malformed floods are shed at the same price as well-formed ones.

v2 adds the two production escape hatches the base mechanism lacks
(docs/operations.md "Binder is under attack"):

- **Allowlists** — config-driven source prefixes that are never
  limited.  Judged inside `decide()` (pre-decode, raw-bytes cost) via
  a per-full-IP verdict cache, so an allowlisted monitoring host or
  anycast peer pays one prefix match ever; allowlisted sources never
  mint buckets, so they cannot be evicted into limiting by a spray.
- **Adaptive buckets** — the NAT'd-resolver-farm fix.  A /24 hiding
  thousands of real clients overdraws its bucket at aggregate qps and
  every one of those drops is a false positive.  But the TC=1 slip is
  a built-in liveness probe: a *real* client retries the slipped query
  over TCP (spoofed floods never complete a handshake).  The stream
  lane reports completed TCP serves via `note_tcp()`; a prefix that
  keeps completing TCP retries *while being limited* accumulates
  evidence and earns a doubled rate multiplier (up to
  ``adaptMaxMultiplier``), converging on just enough headroom that
  limiting stops.  Limited responses charged to a prefix before it
  proved real are attributed to ``false_positives`` — making the RRL
  false-positive rate a measured number, not a guess.
"""
from __future__ import annotations

import logging
import socket
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

DEFAULT_RESPONSES_PER_SECOND = 200.0
DEFAULT_BURST = 400.0
DEFAULT_SLIP_RATIO = 2          # every 2nd limited response slips TC
DEFAULT_PREFIX_V4 = 24
DEFAULT_PREFIX_V6 = 56
#: prefixes tracked at once (LRU) — bounds memory under spoofing
DEFAULT_MAX_BUCKETS = 8192
#: adaptive sizing: rate-multiplier ceiling a TCP-proven prefix can earn
DEFAULT_ADAPT_MAX_MULTIPLIER = 16.0
#: completed TCP serves (while limited) per doubling step
DEFAULT_ADAPT_EVIDENCE = 3

#: decide() verdicts
SEND, SLIP, DROP = 0, 1, 2

#: slip replies echo the request; anything larger than a classic UDP
#: payload is not worth echoing (and drops carry no amplification risk)
_SLIP_MAX_ECHO = 512


class ResponseRateLimiter:
    SEND = SEND
    SLIP = SLIP
    DROP = DROP

    #: hostile-flood flight events are rate-limited to one per window
    FLOOD_EVENT_WINDOW_S = 5.0
    #: hot() stays true this long after the last limited response —
    #: long enough to hold the fastpath gate shut across flood bursts,
    #: short enough that the gate reopens promptly once the flood ends
    HOT_HOLD_S = 2.0
    #: while the fastpath gate is open, 1 in this many UDP readiness
    #: events surfaces to Python so the limiter samples the C-served
    #: stream (each sampled packet charged this many tokens)
    FASTPATH_SAMPLE_EVERY = 8
    #: adapted-prefix records tracked at once — entries exist only for
    #: prefixes that completed a TCP serve while limited, so spoofed
    #: floods (which never complete a handshake) cannot mint them
    ADAPT_MAX_TRACKED = 1024

    def __init__(self, *, enabled: bool = True,
                 responses_per_second: float = DEFAULT_RESPONSES_PER_SECOND,
                 burst: float = DEFAULT_BURST,
                 slip_ratio: int = DEFAULT_SLIP_RATIO,
                 prefix_v4: int = DEFAULT_PREFIX_V4,
                 prefix_v6: int = DEFAULT_PREFIX_V6,
                 max_buckets: int = DEFAULT_MAX_BUCKETS,
                 allowlist: Sequence[str] = (),
                 adaptive: bool = True,
                 adapt_max_multiplier: float = DEFAULT_ADAPT_MAX_MULTIPLIER,
                 adapt_evidence: int = DEFAULT_ADAPT_EVIDENCE,
                 note_shed: Optional[Callable] = None,
                 recorder=None,
                 log: Optional[logging.Logger] = None) -> None:
        self.enabled = bool(enabled)
        self.responses_per_second = float(responses_per_second)
        self.burst = float(burst)
        self.slip_ratio = int(slip_ratio)
        self.prefix_v4 = int(prefix_v4)
        self.prefix_v6 = int(prefix_v6)
        self.max_buckets = int(max_buckets)
        self.adaptive = bool(adaptive)
        self.adapt_max_multiplier = float(adapt_max_multiplier)
        self.adapt_evidence = max(1, int(adapt_evidence))
        self.note_shed = note_shed     # AdmissionControl._note_shed
        self.recorder = recorder
        self.log = log or logging.getLogger("binder.rrl")
        # prefix -> (tokens, last_refill_mono, limited_count);
        # insertion-ordered LRU like admission's client buckets
        self._buckets: Dict[str, Tuple[float, float, int]] = {}
        # full source ip -> prefix string; computing a v6 prefix per
        # packet would be the flood's cost, not the flooder's
        self._prefix_cache: Dict[str, str] = {}
        # allowlist: (packed_network, nbytes, tailmask) per family;
        # per-full-IP verdicts cached so the match runs once per source
        self.allowlist: Tuple[str, ...] = tuple(allowlist or ())
        self._allow_nets_v4: List[Tuple[bytes, int, int]] = []
        self._allow_nets_v6: List[Tuple[bytes, int, int]] = []
        for entry in self.allowlist:
            parsed = self._parse_network(entry)
            if parsed is None:
                self.log.warning("rrl: ignoring bad allowlist entry %r",
                                 entry)
                continue
            (self._allow_nets_v6 if parsed[3] else
             self._allow_nets_v4).append(parsed[:3])
        self._allow_cache: Dict[str, bool] = {}
        # adaptive sizing: prefix -> [multiplier, evidence, limited_cum]
        # — separate from the bucket LRU so a spray that evicts the
        # bucket cannot erase an earned multiplier
        self._adapted: Dict[str, List] = {}
        self._hot_until = 0.0
        self._flood_event_last = 0.0
        #: tokens one decide() charges; the batched UDP reader raises
        #: it to FASTPATH_SAMPLE_EVERY during sampled drain events so
        #: the sampled stream approximates the true per-prefix rate
        self.sample_cost = 1.0
        # fold-ready plain-int counters (scrape-time fold pattern)
        self.responses = 0     # decisions taken (SEND verdicts)
        self.slipped = 0
        self.dropped = 0
        self.evictions = 0
        self.allowlisted = 0   # responses passed by allowlist match
        self.adaptations = 0   # multiplier doubling steps taken
        #: limited responses charged to a prefix *before* it proved
        #: real via TCP completion — the measured false-positive count
        self.false_positives = 0

    @classmethod
    def from_config(cls, config: Optional[dict], *,
                    note_shed=None, recorder=None,
                    log=None) -> Optional["ResponseRateLimiter"]:
        """Build from the ``rrl`` config block; None (or
        ``enabled: false``) disables the layer entirely — the engine
        sees ``rrl=None`` and the UDP lane pays nothing.  An empty
        block means "on, defaults" (the admission-layer convention)."""
        if config is None or not config.get("enabled", True):
            return None
        return cls(
            responses_per_second=config.get(
                "responsesPerSecond", DEFAULT_RESPONSES_PER_SECOND),
            burst=config.get("burst", DEFAULT_BURST),
            slip_ratio=config.get("slipRatio", DEFAULT_SLIP_RATIO),
            prefix_v4=config.get("prefixV4", DEFAULT_PREFIX_V4),
            prefix_v6=config.get("prefixV6", DEFAULT_PREFIX_V6),
            max_buckets=config.get("maxBuckets", DEFAULT_MAX_BUCKETS),
            allowlist=config.get("allowlist", ()),
            adaptive=config.get("adaptive", True),
            adapt_max_multiplier=config.get(
                "adaptMaxMultiplier", DEFAULT_ADAPT_MAX_MULTIPLIER),
            adapt_evidence=config.get(
                "adaptEvidence", DEFAULT_ADAPT_EVIDENCE),
            note_shed=note_shed, recorder=recorder, log=log)

    # -- allowlist --

    @staticmethod
    def _parse_network(entry: str) -> Optional[Tuple[bytes, int, int, bool]]:
        """``"10.0.0.0/8"`` → (packed_network, whole_bytes, tail_mask,
        is_v6); a bare address gets the full-length prefix.  None on
        garbage — config typos must not crash the serve stack."""
        try:
            text, _, bits_s = str(entry).partition("/")
            v6 = ":" in text
            fam = socket.AF_INET6 if v6 else socket.AF_INET
            raw = socket.inet_pton(fam, text.strip())
            width = len(raw) * 8
            bits = int(bits_s) if bits_s else width
            if not 0 <= bits <= width:
                return None
        except (OSError, ValueError):
            return None
        nbytes, rem = divmod(bits, 8)
        tail_mask = (0xFF00 >> rem) & 0xFF if rem else 0
        network = raw[:nbytes + (1 if rem else 0)]
        if rem:
            network = network[:-1] + bytes([network[-1] & tail_mask])
        return (network, nbytes, tail_mask, v6)

    def _allowed(self, ip: str) -> bool:
        """Pre-decode allowlist check: one inet_pton + linear match per
        *new* source IP, a dict hit thereafter.  The verdict cache is
        bounded like every other table here."""
        cached = self._allow_cache.get(ip)
        if cached is not None:
            return cached
        v6 = ":" in ip
        nets = self._allow_nets_v6 if v6 else self._allow_nets_v4
        verdict = False
        if nets:
            try:
                raw = socket.inet_pton(
                    socket.AF_INET6 if v6 else socket.AF_INET, ip)
            except OSError:
                raw = None
            if raw is not None:
                for network, nbytes, tail_mask in nets:
                    if raw[:nbytes] != network[:nbytes]:
                        continue
                    if tail_mask and (raw[nbytes] & tail_mask
                                      != network[nbytes]):
                        continue
                    verdict = True
                    break
        if len(self._allow_cache) >= self.max_buckets:
            self._allow_cache.pop(next(iter(self._allow_cache)))
        self._allow_cache[ip] = verdict
        return verdict

    # -- prefix mapping --

    def _prefix(self, ip: str) -> str:
        cached = self._prefix_cache.get(ip)
        if cached is not None:
            return cached
        if ":" in ip:
            # v6: mask to prefix_v6 bits without the ipaddress module
            # (this runs per flood packet)
            try:
                import socket as _socket
                raw = _socket.inet_pton(_socket.AF_INET6, ip)
                bits = self.prefix_v6
                nbytes, rem = divmod(bits, 8)
                masked = bytearray(raw[:nbytes] + b"\x00" * (16 - nbytes))
                if rem and nbytes < 16:
                    masked[nbytes] = raw[nbytes] & (0xFF00 >> rem & 0xFF)
                prefix = masked.hex() + f"/{bits}"
            except OSError:
                prefix = ip
        else:
            # v4: /24 (or configured) by octet split — no parsing
            keep = max(1, min(4, self.prefix_v4 // 8))
            prefix = ".".join(ip.split(".")[:keep]) + f"/{self.prefix_v4}"
        if len(self._prefix_cache) >= self.max_buckets:
            self._prefix_cache.pop(next(iter(self._prefix_cache)))
        self._prefix_cache[ip] = prefix
        return prefix

    # -- the per-packet decision --

    def decide(self, ip: str) -> int:
        """Charge one response against *ip*'s prefix bucket.

        Returns SEND (answer normally), SLIP (send the TC echo built
        by `slip_reply`), or DROP (silence).  Counts and flight events
        are handled here; the caller only routes the verdict."""
        if not self.enabled:
            return SEND
        if ((self._allow_nets_v4 or self._allow_nets_v6)
                and self._allowed(ip)):
            # never limited, never minting a bucket slot — the spray
            # cannot evict an allowlisted peer into limiting
            self.allowlisted += 1
            return SEND
        now = time.monotonic()
        prefix = self._prefix(ip)
        # TCP-proven prefixes run with an earned rate multiplier; the
        # dict is empty until the first note_tcp() adaptation, so the
        # common path pays one truthiness check
        adapted = self._adapted.get(prefix) if self._adapted else None
        mult = adapted[0] if adapted is not None else 1.0
        burst = self.burst * mult
        entry = self._buckets.pop(prefix, None)
        if entry is None:
            if len(self._buckets) >= self.max_buckets:
                self._buckets.pop(next(iter(self._buckets)))
                self.evictions += 1
            tokens, limited = burst, 0
        else:
            tokens, last, limited = entry
            tokens = min(burst, tokens + (now - last)
                         * self.responses_per_second * mult)
        if tokens >= 1.0:
            self._buckets[prefix] = (tokens - self.sample_cost, now, 0)
            self.responses += 1
            return SEND
        # limited: slip every slip_ratio-th, drop the rest
        limited += 1
        self._buckets[prefix] = (tokens, now, limited)
        if adapted is not None:
            # candidate false positive: this prefix has completed TCP
            # serves before; attributed when the next adaptation lands
            adapted[2] += 1
        self._hot_until = now + self.HOT_HOLD_S
        if (self.recorder is not None
                and now - self._flood_event_last
                >= self.FLOOD_EVENT_WINDOW_S):
            self._flood_event_last = now
            self.recorder.record(
                "hostile-flood", prefix=prefix,
                slipped=self.slipped, dropped=self.dropped,
                buckets=len(self._buckets))
        if self.slip_ratio > 0 and limited % self.slip_ratio == 0:
            self.slipped += 1
            return SLIP
        self.dropped += 1
        if self.note_shed is not None:
            self.note_shed("response-ratelimit", prefix=prefix)
        return DROP

    # -- adaptive sizing (TCP liveness evidence) --

    def note_tcp(self, ip: str) -> None:
        """A TCP query from *ip* was served to completion.

        Called by the stream lane after a successful TCP serve.  While
        a prefix is being limited, each completed TCP serve is proof a
        real client sits behind it — a spoofed source cannot finish the
        handshake the TC=1 slip invites.  ``adapt_evidence`` proofs buy
        one doubling of the prefix's rate multiplier (capped at
        ``adapt_max_multiplier``), and the limited responses the prefix
        absorbed before each doubling are attributed to
        ``false_positives``.  Off the limited path this is one dict
        lookup; evidence only accrues while the prefix's bucket shows
        active limiting, so adapted farms stop growing once they have
        just enough headroom."""
        if not self.enabled or not self.adaptive:
            return
        prefix = self._prefix(ip)
        adapted = self._adapted.get(prefix)
        bucket = self._buckets.get(prefix)
        limiting = bucket is not None and (bucket[0] < 1.0 or bucket[2] > 0)
        if adapted is None:
            if not limiting:
                return      # ordinary TCP traffic, nothing to prove
            if len(self._adapted) >= self.ADAPT_MAX_TRACKED:
                self._adapted.pop(next(iter(self._adapted)))
            # seed the false-positive ledger with the limited streak
            # that pushed this client to TCP in the first place
            adapted = self._adapted[prefix] = [1.0, 0, bucket[2]]
        elif not limiting:
            return
        adapted[1] += 1
        if (adapted[1] < self.adapt_evidence
                or adapted[0] >= self.adapt_max_multiplier):
            return
        adapted[1] = 0
        adapted[0] = min(self.adapt_max_multiplier, adapted[0] * 2.0)
        self.adaptations += 1
        self.false_positives += adapted[2]
        fp = adapted[2]
        adapted[2] = 0
        if self.recorder is not None:
            self.recorder.record(
                "rrl-adapt", prefix=prefix, multiplier=adapted[0],
                false_positives=fp)
        self.log.info("rrl: adapted %s to %.0fx (%d limited responses "
                      "attributed as false positives)",
                      prefix, adapted[0], fp)

    def adapted_count(self) -> int:
        """Prefixes currently holding an earned multiplier > 1 — the
        ``binder_rrl_adapted_buckets`` gauge."""
        return sum(1 for v in self._adapted.values() if v[0] > 1.0)

    @staticmethod
    def slip_reply(data: bytes) -> Optional[bytes]:
        """TC=1 echo of the request — the RRL slip.

        Byte-2 keeps opcode+RD, sets QR|TC, clears AA; byte-3 zeroes
        RA/Z/rcode.  The body is echoed verbatim, so the reply is never
        larger than the query (negative amplification) and a legit
        client's resolver sees its own question with TC and retries
        over TCP.  None (caller drops) for headerless or oversized
        frames — nothing legitimate sends either."""
        if len(data) < 12 or len(data) > _SLIP_MAX_ECHO:
            return None
        b = bytearray(data)
        b[2] = 0x80 | (b[2] & 0x79) | 0x02
        b[3] = 0x00
        return bytes(b)

    # -- state for the fastpath gate coupling --

    def hot(self) -> bool:
        """True while limiting happened within HOT_HOLD_S — the signal
        BinderServer uses to keep the C fastpath gate shut so every
        packet surfaces to Python for per-prefix judgment."""
        return time.monotonic() < self._hot_until

    # -- introspection (status.py `policy.rrl`) --

    def introspect(self) -> dict:
        return {
            "enabled": self.enabled,
            "responses_per_second": self.responses_per_second,
            "burst": self.burst,
            "slip_ratio": self.slip_ratio,
            "prefix_v4": self.prefix_v4,
            "prefix_v6": self.prefix_v6,
            "max_buckets": self.max_buckets,
            "buckets": len(self._buckets),
            "hot": self.hot(),
            "responses": self.responses,
            "slipped": self.slipped,
            "dropped": self.dropped,
            "evictions": self.evictions,
            "allowlist": list(self.allowlist),
            "allowlisted": self.allowlisted,
            "adaptive": self.adaptive,
            "adapted_buckets": self.adapted_count(),
            "adaptations": self.adaptations,
            "false_positives": self.false_positives,
        }
