"""Graceful-degradation policy engine (docs/degradation.md).

Three cooperating levers, consuming PR 2's observability substrate:

- :class:`DegradationPolicy` — stale-serve state machine over the
  store session state (fresh / stale-serving / stale-exhausted),
  RFC 8767-style TTL clamping and a hard staleness cap;
- :class:`PeerBreakers` / :class:`CircuitBreaker` — per-upstream
  circuit breakers with exponential backoff + jitter, half-open
  probing, and the p95 hedge stagger for recursion forwards;
- :class:`AdmissionControl` — overload shedding: bounded in-flight
  table with oldest-shed and per-client token buckets for
  recursion-triggering queries;
- :class:`ResponseRateLimiter` — RRL-style per-client-prefix
  slip/drop at the UDP ingress (hostile-internet hardening).
"""
from binder_tpu.policy.admission import AdmissionControl
from binder_tpu.policy.breaker import CircuitBreaker, PeerBreakers
from binder_tpu.policy.degrade import DegradationPolicy
from binder_tpu.policy.rrl import ResponseRateLimiter

__all__ = ["AdmissionControl", "CircuitBreaker", "PeerBreakers",
           "DegradationPolicy", "ResponseRateLimiter"]
