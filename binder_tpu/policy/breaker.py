"""Per-peer circuit breakers + latency tracking for recursion upstreams.

The reference forwards cross-DC queries with a flat 3 s timeout per
upstream and no memory between queries (``lib/recursion.js:253-279``):
a dead remote binder costs every single query the full timeout before
the next resolver is tried, and the dead peer keeps being retried at
full rate — exactly the uncontrolled upstream fan-out NXNSAttack
(PAPERS.md) shows amplifying a remote failure into a local outage.

This module gives each upstream peer a classic three-state breaker:

- **closed** — normal serving; consecutive transport failures are
  counted and ``FAILURE_THRESHOLD`` of them open the breaker.
- **open** — the peer is skipped outright (a query to a DC whose only
  peer is open fails over to REFUSED in well under a millisecond — the
  "<100 ms once the breaker is open" guarantee, pinned by
  tests/test_chaos.py).  The open interval backs off exponentially
  with full jitter (cap ``BACKOFF_CAP``) so a herd of binders doesn't
  re-probe a recovering peer in lockstep.
- **half-open** — after the backoff expires exactly ONE probe query is
  let through; success closes the breaker and resets the backoff,
  failure re-opens it at the next backoff step.

An *rcode* error (REFUSED, NXDOMAIN...) is a *response*: the peer is
alive and the breaker records success — breakers track transport
liveness, not answer quality.

Latency tracking rides along: a bounded ring of recent RTTs per peer
feeds ``hedge_delay()``, the p95-based stagger the DNS client uses to
launch a hedged second request instead of waiting out the full serial
timeout (``recursion/client.py``).

Every transition emits a ``breaker-transition`` flight-recorder event
and updates ``binder_breaker_state`` (0 closed / 1 half-open / 2 open,
labelled by peer, plus an always-present ``peer="(max)"`` aggregate
series alerting rules can key on without knowing peer names).
"""
from __future__ import annotations

import logging
import random
import time
from typing import Dict, List, Optional, Sequence

#: breaker state encoding for binder_breaker_state (docs/degradation.md)
STATE_CODES = {"closed": 0, "half-open": 1, "open": 2}


class CircuitBreaker:
    """One peer's breaker + latency ring.  Single-threaded (event-loop
    owned), monotonic-clock based."""

    FAILURE_THRESHOLD = 3
    BACKOFF_BASE = 1.0      # first open interval (seconds)
    BACKOFF_CAP = 30.0      # backoff ceiling
    LATENCY_RING = 64       # recent RTT samples kept for the p95
    #: half-open probe admission rate: one probe per interval.  Rate-
    #: based rather than one-outstanding-at-a-time on purpose — a probe
    #: whose outcome is never reported back (winner raced it, task
    #: cancelled mid-flight) must not wedge the breaker half-open
    #: forever.
    PROBE_INTERVAL = 1.0

    __slots__ = ("peer", "state", "failures", "consecutive", "successes",
                 "opened_at", "open_until", "_backoff", "_last_probe",
                 "_lat", "_lat_i", "transitions", "_rng", "_on_transition")

    def __init__(self, peer: str, rng: Optional[random.Random] = None,
                 on_transition=None) -> None:
        self.peer = peer
        self.state = "closed"
        self.failures = 0          # total transport failures ever
        self.consecutive = 0       # current consecutive-failure run
        self.successes = 0
        self.opened_at: Optional[float] = None
        self.open_until = 0.0
        self._backoff = self.BACKOFF_BASE
        self._last_probe = 0.0
        self._lat: List[float] = []
        self._lat_i = 0
        self.transitions = 0
        self._rng = rng or random.Random()
        self._on_transition = on_transition

    # -- admission --

    def allow(self, now: Optional[float] = None) -> bool:
        """May a query be sent to this peer right now?  In the open
        state this flips to half-open (and admits a probe) once the
        backoff interval has elapsed."""
        if self.state == "closed":
            return True
        now = time.monotonic() if now is None else now
        if self.state == "open":
            if now < self.open_until:
                return False
            self._transition("half-open")
            self._last_probe = now
            return True
        # half-open: one probe per PROBE_INTERVAL
        if now - self._last_probe < self.PROBE_INTERVAL:
            return False
        self._last_probe = now
        return True

    # -- outcome feedback --

    def record_success(self, latency_s: Optional[float] = None) -> None:
        self.successes += 1
        self.consecutive = 0
        if latency_s is not None:
            if len(self._lat) < self.LATENCY_RING:
                self._lat.append(latency_s)
            else:
                self._lat[self._lat_i] = latency_s
                self._lat_i = (self._lat_i + 1) % self.LATENCY_RING
        if self.state != "closed":
            self._backoff = self.BACKOFF_BASE
            self._transition("closed")

    def record_failure(self, now: Optional[float] = None) -> None:
        self.failures += 1
        self.consecutive += 1
        now = time.monotonic() if now is None else now
        if self.state == "half-open":
            # failed probe: re-open at the next backoff step
            self._backoff = min(self._backoff * 2, self.BACKOFF_CAP)
            self._open(now)
        elif (self.state == "closed"
                and self.consecutive >= self.FAILURE_THRESHOLD):
            self._open(now)

    def _open(self, now: float) -> None:
        self.opened_at = now
        # full jitter (0.5x..1x of the backoff): decorrelates probe
        # herds across the N-process deployment unit
        self.open_until = now + self._backoff * (
            0.5 + 0.5 * self._rng.random())
        self._transition("open")

    def _transition(self, new: str) -> None:
        old, self.state = self.state, new
        self.transitions += 1
        if self._on_transition is not None:
            self._on_transition(self, old, new)

    # -- latency / introspection --

    def p95_latency(self) -> Optional[float]:
        if not self._lat:
            return None
        ordered = sorted(self._lat)
        return ordered[min(len(ordered) - 1,
                           int(len(ordered) * 0.95))]

    def introspect(self) -> dict:
        return {
            "state": self.state,
            "failures": self.failures,
            "consecutive_failures": self.consecutive,
            "successes": self.successes,
            "backoff_seconds": self._backoff,
            "open_remaining_seconds": (
                max(0.0, self.open_until - time.monotonic())
                if self.state == "open" else 0.0),
            "p95_latency_ms": (None if self.p95_latency() is None
                               else self.p95_latency() * 1000.0),
        }


class PeerBreakers:
    """Breaker registry keyed by resolver string ("ip" / "ip:port").

    Shared by both recursion DNS clients (the bounded-concurrency
    forwarder and the PTR fan-out client) so a peer's health is one
    fact, not two.  Registered peers get a ``binder_breaker_state``
    series; an LRU bound keeps a rogue resolver-discovery source from
    minting unbounded series."""

    MAX_PEERS = 256
    #: hedge stagger bounds: never hedge sooner than the floor (a p95
    #: of microseconds would hedge every query), never later than the
    #: cap (the whole point is beating the 3 s serial timeout)
    HEDGE_FLOOR = 0.05
    HEDGE_CAP = 1.0
    #: stagger used before a peer has any latency samples
    HEDGE_DEFAULT = 0.25

    def __init__(self, collector=None, recorder=None,
                 log: Optional[logging.Logger] = None,
                 rng: Optional[random.Random] = None) -> None:
        self.log = log or logging.getLogger("binder.breaker")
        self.recorder = recorder
        self._rng = rng or random.Random()
        self._peers: Dict[str, CircuitBreaker] = {}
        self._gauge = None
        if collector is not None:
            self._gauge = collector.gauge(
                "binder_breaker_state",
                "per-peer circuit breaker state (0 closed, 1 half-open, "
                "2 open); peer=\"(max)\" aggregates the worst peer")
            # the aggregate series exists from scrape 1, peers or not —
            # alerting rules key on it without knowing peer addresses
            self._gauge.set_function(self._max_state_code,
                                     {"peer": "(max)"})

    def _max_state_code(self) -> float:
        return float(max((STATE_CODES[b.state]
                          for b in self._peers.values()), default=0))

    def _note_transition(self, breaker: CircuitBreaker, old: str,
                         new: str) -> None:
        if self.recorder is not None:
            self.recorder.record("breaker-transition", peer=breaker.peer,
                                 frm=old, to=new,
                                 consecutive=breaker.consecutive)
        if new == "open":
            self.log.warning(
                "circuit breaker OPEN for upstream %s after %d "
                "consecutive failures (backoff %.1fs)", breaker.peer,
                breaker.consecutive, breaker._backoff)
        elif new == "closed" and old != "closed":
            self.log.info("circuit breaker closed for upstream %s",
                          breaker.peer)

    def get(self, peer: str) -> CircuitBreaker:
        b = self._peers.get(peer)
        if b is None:
            if len(self._peers) >= self.MAX_PEERS:
                self._peers.pop(next(iter(self._peers)))
            b = CircuitBreaker(peer, rng=self._rng,
                               on_transition=self._note_transition)
            self._peers[peer] = b
            if self._gauge is not None:
                self._gauge.set_function(
                    lambda b=b: float(STATE_CODES[b.state]),
                    {"peer": peer})
        return b

    # -- client-facing policy --

    def filter(self, resolvers: Sequence[str]) -> List[str]:
        """The resolvers a lookup may use right now: closed peers
        first, then half-open probes; open (not yet probe-eligible)
        peers are skipped.  An empty result means every peer is open —
        the lookup fails fast (well-formed refusal) instead of
        hanging, and the next backoff expiry re-probes."""
        closed, probing = [], []
        now = time.monotonic()
        for r in resolvers:
            b = self._peers.get(r)
            if b is None or b.state == "closed":
                closed.append(r)
            elif b.allow(now):
                probing.append(r)
        return closed + probing

    def hedge_delay(self, peer: str) -> float:
        """How long to wait on *peer* before launching the next
        upstream: p95 of its recent RTTs (x1.5 headroom), clamped —
        the RFC-style hedged request stagger."""
        b = self._peers.get(peer)
        p95 = b.p95_latency() if b is not None else None
        if p95 is None:
            return self.HEDGE_DEFAULT
        return min(max(p95 * 1.5, self.HEDGE_FLOOR), self.HEDGE_CAP)

    def record(self, peer: str, ok: bool,
               latency_s: Optional[float] = None) -> None:
        b = self.get(peer)
        if ok:
            b.record_success(latency_s)
        else:
            b.record_failure()

    def open_count(self) -> int:
        return sum(1 for b in self._peers.values() if b.state == "open")

    def introspect(self) -> dict:
        return {peer: b.introspect()
                for peer, b in self._peers.items()}
