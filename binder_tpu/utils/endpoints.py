"""Shared "host[:port]" / "[v6][:port]" endpoint parsing.

One implementation for every surface that names network endpoints as
strings: resolver lists (reference ``lib/recursion.js`` resolver
entries) and ZooKeeper connect strings (reference deployment shape,
``README.md:36-39``).
"""
from __future__ import annotations

from typing import Tuple


def parse_endpoint(entry: str, default_port: int) -> Tuple[str, int]:
    """``"h"``, ``"h:53"``, ``"[::1]"``, ``"[::1]:53"``, bare ``"::1"``."""
    entry = entry.strip()
    if entry.startswith("["):
        host, _, port_s = entry[1:].partition("]")
        port_s = port_s.lstrip(":")
        return host, int(port_s) if port_s else default_port
    if entry.count(":") == 1:          # v4/hostname with port
        host, _, port_s = entry.partition(":")
        return host, int(port_s)
    return entry, default_port         # bare host (incl. bare v6)
