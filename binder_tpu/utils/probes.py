"""Tracing probes — the rebuild's answer to the reference's DTrace USDT
provider (``lib/server.js:24-29``: provider ``binder``, probes
``op-req-start`` / ``op-req-done`` fired per query with lazily-built
JSON arguments).

Linux has no USDT-from-script equivalent, so the provider here is a
pluggable fan-out with the same two properties the reference relies on:

- **zero cost when disabled** — ``fire()`` takes a *callable* producing
  the probe arguments, evaluated only if some backend is attached
  (dtrace's ``p1.fire(function () { return [query]; })`` semantics);
- **observable from outside the process** — the ``ftrace`` backend
  writes ``binder:<probe>: <json>`` markers to the kernel trace buffer
  (``/sys/kernel/tracing/trace_marker``), visible in ``trace-cmd`` /
  ``perfetto`` alongside scheduler events, which is how the dtrace
  one-liners in the reference's runbooks translate.

In-process consumers (tests, a future ``binder-dtrace`` analog) use
``subscribe``.  Backend selection: ``BINDER_PROBES=ftrace|log|off``
(default off).
"""
from __future__ import annotations

import json
import logging
import os
import threading
from typing import Callable, Dict, List, Optional

log = logging.getLogger("binder.probes")


class Probe:
    __slots__ = ("name", "provider")

    def __init__(self, provider: "ProbeProvider", name: str) -> None:
        self.provider = provider
        self.name = name

    @property
    def enabled(self) -> bool:
        return bool(self.provider._sinks)

    def fire(self, argf: Callable[[], object]) -> None:
        """Evaluate ``argf`` and deliver only if somebody is listening."""
        # snapshot: _sinks is replaced wholesale (copy-on-write under the
        # provider lock), never mutated in place, so this local reference
        # is a stable list even while another thread (a test detaching
        # its sink mid-load, the ftrace close path) subscribes or
        # unsubscribes concurrently — no sink is skipped, nothing raises
        sinks = self.provider._sinks
        if not sinks:
            return
        try:
            args = argf()
        except Exception as e:  # noqa: BLE001 — probes must never take
            log.debug("probe %s argf failed: %s", self.name, e)  # down serving
            return
        for sink in sinks:
            try:
                sink(self.name, args)
            except Exception as e:  # noqa: BLE001
                log.debug("probe sink failed for %s: %s", self.name, e)


class ProbeProvider:
    """``provider.probe("op-req-start").fire(lambda: {...})``."""

    TRACE_MARKER_PATHS = (
        "/sys/kernel/tracing/trace_marker",
        "/sys/kernel/debug/tracing/trace_marker",
    )

    def __init__(self, name: str = "binder",
                 backend: Optional[str] = None) -> None:
        self.name = name
        self._probes: Dict[str, Probe] = {}
        # copy-on-write: mutated only by replacement under _sinks_lock;
        # Probe.fire() iterates a snapshot reference without the lock
        self._sinks: List[Callable[[str, object], None]] = []
        self._sinks_lock = threading.Lock()
        self._marker = None
        backend = (backend if backend is not None
                   else os.environ.get("BINDER_PROBES", "off")).lower()
        if backend == "ftrace":
            self._attach_ftrace()
        elif backend == "log":
            self.subscribe(self._log_sink)
        # anything else (off/unknown): no sinks, probes disabled

    def probe(self, probe_name: str) -> Probe:
        p = self._probes.get(probe_name)
        if p is None:
            p = self._probes[probe_name] = Probe(self, probe_name)
        return p

    def subscribe(self, fn: Callable[[str, object], None]) -> None:
        with self._sinks_lock:
            self._sinks = self._sinks + [fn]

    def unsubscribe(self, fn: Callable[[str, object], None]) -> None:
        with self._sinks_lock:
            sinks = list(self._sinks)
            try:
                sinks.remove(fn)
            except ValueError:
                return
            self._sinks = sinks

    # -- backends --

    def _attach_ftrace(self) -> None:
        for path in self.TRACE_MARKER_PATHS:
            try:
                self._marker = open(path, "w", buffering=1)
                self.subscribe(self._ftrace_sink)
                log.info("probes: ftrace markers to %s", path)
                return
            except OSError:
                continue
        log.warning("probes: BINDER_PROBES=ftrace but no writable "
                    "trace_marker; probes disabled")

    def _ftrace_sink(self, probe_name: str, args: object) -> None:
        try:
            self._marker.write(
                f"{self.name}:{probe_name}: "
                f"{json.dumps(args, default=str, separators=(',', ':'))}\n")
        except OSError:
            pass

    def _log_sink(self, probe_name: str, args: object) -> None:
        log.info("%s:%s: %s", self.name, probe_name,
                 json.dumps(args, default=str, separators=(",", ":")))

    def close(self) -> None:
        if self._marker is not None:
            # detach the sink too, or probes stay 'enabled' and fire
            # into the closed file on every query
            self.unsubscribe(self._ftrace_sink)
            try:
                self._marker.close()
            except OSError:
                pass
            self._marker = None
