"""Structured JSON logging (bunyan-equivalent).

The reference logs bunyan JSON lines to stdout with numeric levels
(``main.js:40-47``); operators filter with the ``bunyan`` CLI.  This module
emits the same shape — one JSON object per line with ``name``, ``hostname``,
``pid``, ``level`` (bunyan numeric scale), ``msg``, ``time``, plus any
structured fields — so existing log tooling keeps working.
"""
from __future__ import annotations

import datetime
import json
import logging
import os
import socket
import sys
from typing import IO, Optional

# bunyan numeric levels
BUNYAN_LEVELS = {
    logging.DEBUG - 5: 10,   # trace
    logging.DEBUG: 20,
    logging.INFO: 30,
    logging.WARNING: 40,
    logging.ERROR: 50,
    logging.CRITICAL: 60,
}

TRACE = logging.DEBUG - 5
logging.addLevelName(TRACE, "TRACE")


class JsonFormatter(logging.Formatter):
    def __init__(self, name: str) -> None:
        super().__init__()
        self.name = name
        self.hostname = socket.gethostname()

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "name": self.name,
            "hostname": self.hostname,
            "pid": os.getpid(),
            "level": BUNYAN_LEVELS.get(record.levelno,
                                       record.levelno),
            "component": record.name,
            "msg": record.getMessage(),
            "time": datetime.datetime.now(datetime.timezone.utc)
                    .isoformat().replace("+00:00", "Z"),
            "v": 0,
        }
        extra = getattr(record, "binder", None)
        if isinstance(extra, dict):
            entry.update(extra)
        if record.exc_info and record.exc_info[0] is not None:
            entry["err"] = {
                "name": record.exc_info[0].__name__,
                "message": str(record.exc_info[1]),
            }
        return json.dumps(entry, default=str)


def make_logger(name: str = "binder", level: str = "info",
                stream: Optional[IO] = None) -> logging.Logger:
    """Create the root service logger with bunyan-style JSON output."""
    logger = logging.getLogger(name)
    logger.setLevel(_parse_level(level))
    logger.propagate = False
    handler = logging.StreamHandler(stream or sys.stdout)
    handler.setFormatter(JsonFormatter(name))
    logger.handlers = [handler]
    return logger


def _parse_level(level: str) -> int:
    return {
        "trace": TRACE,
        "debug": logging.DEBUG,
        "info": logging.INFO,
        "warn": logging.WARNING,
        "warning": logging.WARNING,
        "error": logging.ERROR,
        "fatal": logging.CRITICAL,
    }.get(str(level).lower(), logging.INFO)


def log_event(logger: logging.Logger, level: int, msg: str,
              **fields) -> None:
    """Log *msg* with structured *fields* merged into the JSON line."""
    logger.log(level, msg, extra={"binder": fields})
