"""Local NIC address enumeration (os.networkInterfaces() equivalent).

The recursion layer filters its own addresses out of the upstream resolver
list to avoid recursing into itself (reference ``lib/recursion.js:356-376``,
with a 30s cache).  Python's stdlib has no getifaddrs binding, so this uses
ctypes against libc on Linux, with a getaddrinfo fallback.
"""
from __future__ import annotations

import ctypes
import ctypes.util
import socket
from typing import List

AF_INET = socket.AF_INET
AF_INET6 = socket.AF_INET6


class _sockaddr(ctypes.Structure):
    _fields_ = [("sa_family", ctypes.c_ushort),
                ("sa_data", ctypes.c_char * 14)]


class _sockaddr_in(ctypes.Structure):
    _fields_ = [("sin_family", ctypes.c_ushort),
                ("sin_port", ctypes.c_uint16),
                ("sin_addr", ctypes.c_ubyte * 4)]


class _sockaddr_in6(ctypes.Structure):
    _fields_ = [("sin6_family", ctypes.c_ushort),
                ("sin6_port", ctypes.c_uint16),
                ("sin6_flowinfo", ctypes.c_uint32),
                ("sin6_addr", ctypes.c_ubyte * 16)]


class _ifaddrs(ctypes.Structure):
    pass


_ifaddrs._fields_ = [
    ("ifa_next", ctypes.POINTER(_ifaddrs)),
    ("ifa_name", ctypes.c_char_p),
    ("ifa_flags", ctypes.c_uint),
    ("ifa_addr", ctypes.POINTER(_sockaddr)),
    ("ifa_netmask", ctypes.POINTER(_sockaddr)),
    ("ifa_ifu", ctypes.POINTER(_sockaddr)),
    ("ifa_data", ctypes.c_void_p),
]


def local_addresses() -> List[str]:
    """All IPv4/IPv6 addresses assigned to local interfaces."""
    try:
        return _getifaddrs()
    except (OSError, AttributeError):
        return _fallback()


def _getifaddrs() -> List[str]:
    libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6",
                       use_errno=True)
    addrlist = ctypes.POINTER(_ifaddrs)()
    if libc.getifaddrs(ctypes.byref(addrlist)) != 0:
        raise OSError(ctypes.get_errno(), "getifaddrs failed")
    out: List[str] = []
    try:
        node = addrlist
        while node:
            ifa = node.contents
            sa = ifa.ifa_addr
            if sa:
                family = sa.contents.sa_family
                if family == AF_INET:
                    sin = ctypes.cast(sa,
                                      ctypes.POINTER(_sockaddr_in)).contents
                    out.append(socket.inet_ntop(AF_INET,
                                                bytes(sin.sin_addr)))
                elif family == AF_INET6:
                    sin6 = ctypes.cast(
                        sa, ctypes.POINTER(_sockaddr_in6)).contents
                    out.append(socket.inet_ntop(AF_INET6,
                                                bytes(sin6.sin6_addr)))
            node = ifa.ifa_next
    finally:
        libc.freeifaddrs(addrlist)
    return out


def _fallback() -> List[str]:
    out = ["127.0.0.1", "::1"]
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None):
            addr = info[4][0]
            if addr not in out:
                out.append(addr)
    except socket.gaierror:
        pass
    return out
