"""Query-resolution engine — the business logic of the DNS service.

Port of the reference's ``lib/server.js`` ``resolve()`` (:136-429) and
``resolvePtr()`` (:67-134), preserving its deliberate, failover-oriented
rcode policy exactly (SURVEY §7.3 calls these "behaviorally load-bearing"):

- Names outside the DNS domain, invalid names, SRV-shaped names that don't
  parse, and cache misses (without recursion) are **REFUSED**, not
  NXDOMAIN/NODATA, so downstream resolvers fail over to their next
  nameserver instead of erroring out (comment at ``lib/server.js:227-241``).
- The store being unavailable is **SERVFAIL** (``lib/server.js:186-192``).
- An SRV query for a name we own that isn't a service gets NOERROR +
  SOA authority (NODATA with negative-caching TTL, ``lib/server.js:276-292``).
- An SRV query whose service/proto labels don't match the registered ones
  is **NXDOMAIN** (``lib/server.js:334-345``).
- TTL precedence is three-level, deepest-object-wins: default 30s ←
  record.ttl ← record[type].ttl, plus the nested ``service.service`` case
  (``lib/server.js:262-274,326-332``) and min(service-ttl, member-ttl) for
  plain-A service answers (``lib/server.js:403-414``).

Known deviation: the reference's "doubled-up dns domain suffix" REFUSED
check (``lib/server.js:167-175``) is dead code — its ``stripSuffix`` helper
appends ``'...'`` to the stripped name, so the subsequent ``isSuffix`` never
matches.  We implement the evident intent (refuse ``x.foo.com.foo.com`` and
``x.foo.com.<dc>.foo.com``); the externally visible rcode is REFUSED either
way (the reference would miss the cache and refuse too), but we skip the
pointless recursion attempt the reference would make.
"""
from __future__ import annotations

import logging
import random
import re
from typing import Optional
from urllib.parse import urlparse

from binder_tpu.dns.query import QueryCtx
from binder_tpu.dns.wire import (
    ARecord,
    PTRRecord,
    Rcode,
    SOARecord,
    SRVRecord,
    Type,
)
from binder_tpu.store.cache import MirrorCache

SRV_RE = re.compile(r"^(_[^_.]*)\.(_[^_.]*)\.(.*)$")
NAME_RE = re.compile(r"[^a-z0-9_.-]")

# Child record types eligible to back a service answer
# (lib/server.js:352-360 — note: plain 'host' and 'db_host' are excluded).
SERVICE_CHILD_TYPES = frozenset({
    "load_balancer", "moray_host", "ops_host", "rr_host", "redis_host",
})

DEFAULT_TTL = 30  # reference lib/server.js:270 (the ZK session timeout)


def _is_suffix(suffix: str, s: str) -> bool:
    return s.endswith(suffix)


def _record_ttl(record: dict, sub: dict, default: int = DEFAULT_TTL) -> int:
    """Deepest-object-wins TTL precedence (lib/server.js:262-274)."""
    ttl = default
    if isinstance(record, dict) and record.get("ttl") is not None:
        ttl = record["ttl"]
    if isinstance(sub, dict) and sub.get("ttl") is not None:
        ttl = sub["ttl"]
    return ttl


def _valid_record(record) -> bool:
    """Record must be a dict with a string type and an object sub-record
    (lib/server.js:251-259)."""
    return (isinstance(record, dict)
            and isinstance(record.get("type"), str)
            and isinstance(record.get(record["type"]), dict))


class Resolver:
    """Stateless resolution engine over a mirror cache (+ optional
    recursion)."""

    def __init__(self, zk_cache: MirrorCache, dns_domain: str,
                 datacenter_name: str = "",
                 recursion=None,
                 log: Optional[logging.Logger] = None,
                 rng: Optional[random.Random] = None) -> None:
        self.cache = zk_cache
        self.dns_domain = dns_domain.lower() if dns_domain else ""
        self.datacenter_name = datacenter_name
        self.recursion = recursion
        self.log = log or logging.getLogger("binder.resolver")
        self.rng = rng or random.Random()

    # -- entry point used by the server engine (lib/server.js:491-506) --
    #
    # Synchronous: cache-served queries complete inline (the hot path);
    # only the recursion handoff returns an awaitable for the caller to
    # drive (cross-DC network I/O).

    def handle(self, query: QueryCtx):
        qt = query.qtype()
        if qt in (Type.A, Type.SRV):
            return self.resolve(query)
        if qt == Type.PTR:
            return self.resolve_ptr(query)
        # anything unsupported we tell the client the truth
        query.set_error(Rcode.NOTIMP)
        query.respond()
        return None

    # -- forward resolution (lib/server.js:136-429) --

    def resolve(self, query: QueryCtx):
        domain = query.name()

        service = protocol = None
        m = SRV_RE.match(domain)
        if query.qtype() == Type.SRV:
            if not m or len(m.group(3)) < 1:
                query.log_ctx["reason"] = "not a valid SRV lookup domain"
                query.set_error(Rcode.REFUSED)
                query.respond()
                return
            service, protocol, domain = m.group(1), m.group(2), m.group(3)

        if self.dns_domain:
            if _is_suffix("." + self.dns_domain, domain):
                stripped = domain[:-(len(self.dns_domain) + 1)]
            else:
                query.log_ctx["reason"] = "not within dns domain suffix"
                query.set_error(Rcode.REFUSED)
                query.respond()
                return
            dcsuff = self.dns_domain + "." + self.datacenter_name
            if (stripped == self.dns_domain
                    or _is_suffix("." + self.dns_domain, stripped)
                    or stripped == dcsuff
                    or _is_suffix("." + dcsuff, stripped)):
                query.log_ctx["reason"] = "doubled-up dns domain suffix"
                query.set_error(Rcode.REFUSED)
                query.respond()
                return

        query.log_ctx["query"] = {
            "srv": f"{service}.{protocol}" if service else None,
            "name": domain,
            "type": query.qtype_name(),
        }

        if not self.cache.is_ready():
            self.log.error("no coordination-store session")
            query.set_error(Rcode.SERVFAIL)
            query.respond()
            return

        if len(domain) < 1:
            query.set_error(Rcode.REFUSED)
            query.respond()
            return

        domain = domain.lower()
        if NAME_RE.search(domain):
            query.log_ctx["reason"] = "invalid name"
            query.set_error(Rcode.REFUSED)
            query.respond()
            return

        # dependency tag for the answer caches: whatever this lookup
        # yields (including a miss-REFUSED) changes when `domain`
        # mutates in the store — note for SRV this is the *service node*
        # domain, not the _svc._proto-prefixed qname
        query.dep_domain = domain
        # traced: stamps "store-lookup" (decode→policy→mirror probe) on
        # the query's attribution timeline
        node = self.cache.lookup_traced(domain, query)

        if node is None:
            if self.recursion is not None and query.rd():
                # recursion answers belong to another DC's store — no
                # cache layer may keep them (query.no_store reaches the
                # balancer as the do-not-store transport marker)
                query.no_store = True
                return self.recursion.resolve(query)
            # REFUSED, not NXDOMAIN: clients must fail over to their next
            # nameserver (lib/server.js:227-241)
            query.set_error(Rcode.REFUSED)
            query.stamp("pre-resp")
            query.respond()
            return

        record = node.data
        if not _valid_record(record):
            self.log.error("invalid store record at %s: %r", domain, record)
            query.set_error(Rcode.SERVFAIL)
            query.stamp("pre-resp")
            query.respond()
            return

        sub = record[record["type"]]
        ttl = _record_ttl(record, sub)

        if service is not None and record["type"] != "service":
            # SRV on a non-service name we own: NODATA + SOA for negative
            # caching (lib/server.js:276-292)
            query.set_error(Rcode.NOERROR)
            query.add_authority(SOARecord(
                name=domain, ttl=ttl, mname=self.dns_domain, minimum=ttl))
            query.stamp("build_response")
            query.respond()
            return

        rtype = record["type"]
        if rtype == "database":
            addr = urlparse(sub.get("primary", "")).hostname
            query.add_answer(ARecord(name=domain, ttl=ttl, address=addr))
        elif rtype in ("db_host", "host", "load_balancer", "moray_host",
                       "redis_host", "ops_host", "rr_host"):
            query.add_answer(ARecord(name=domain, ttl=ttl,
                                     address=sub.get("address")))
        elif rtype == "service":
            self._resolve_service(query, node, record, domain,
                                  service, protocol, ttl)
        else:
            self.log.error("record type %r in store is unknown", rtype)

        query.stamp("pre-resp")
        query.respond()

    def _resolve_service(self, query: QueryCtx, node, record: dict,
                         domain: str, service: Optional[str],
                         protocol: Optional[str], ttl: int) -> None:
        s = record["service"]
        if isinstance(s.get("service"), dict):
            # nested historical format; TTL may live here too
            s = s["service"]
        if s.get("ttl") is not None:
            ttl = s["ttl"]

        if service is not None and (service != s.get("srvce")
                                    or protocol != s.get("proto")):
            # SRV for a service/proto that doesn't match the registered
            # one: we own the name, so NXDOMAIN (lib/server.js:334-345)
            query.set_error(Rcode.NXDOMAIN)
            return

        # explicit NOERROR so an empty service doesn't fall through
        # (lib/server.js:347-351)
        query.set_error(Rcode.NOERROR)

        kids = [k for k in node.children
                if isinstance(k.data, dict)
                and k.data.get("type") in SERVICE_CHILD_TYPES]
        self.rng.shuffle(kids)

        for knode in kids:
            krec = knode.data
            if not _valid_record(krec):
                query.set_error(Rcode.SERVFAIL)
                self.log.error("bad store info under %s", domain)
                break
            ksub = krec[krec["type"]]
            addr = ksub.get("address")
            if addr is None:
                continue
            ports = ksub.get("ports")
            if not ports:
                ports = [s.get("port")]
            rttl = _record_ttl(krec, ksub, ttl)

            if service is not None:
                nm = f"{knode.name}.{domain}"
                for p in ports:
                    query.add_answer(SRVRecord(
                        name=query.name(), ttl=ttl, priority=0, weight=10,
                        port=p, target=nm))
                query.add_additional(ARecord(name=nm, ttl=rttl, address=addr))
            else:
                # plain A for a service: membership AND address — use the
                # smaller of the two TTLs (lib/server.js:403-414)
                query.add_answer(ARecord(name=domain, ttl=min(ttl, rttl),
                                         address=addr))

    # -- reverse resolution (lib/server.js:67-134) --

    def resolve_ptr(self, query: QueryCtx):
        domain = query.name()
        parts = list(reversed(domain.split(".")))
        if len(parts) < 2 or parts[0] != "arpa" or parts[1] != "in-addr":
            # v6 reverse names included: the reference only serves IPv4 PTR
            query.log_ctx["reason"] = "not an ipv4 reverse name"
            query.set_error(Rcode.REFUSED)
            query.respond()
            return
        # No octet validation: an invalid address simply misses the cache
        # and is REFUSED, so the client tries its next NS
        # (comment at lib/server.js:79-83)
        ip = ".".join(parts[2:])

        if not self.cache.is_ready():
            self.log.error("no coordination-store session")
            query.set_error(Rcode.SERVFAIL)
            query.respond()
            return

        query.log_ctx["query"] = {"ip": ip, "type": query.qtype_name()}

        # dependency tag: mutations touching this address emit the
        # normalized reverse qname (store/cache.py _rev_name)
        query.dep_domain = domain.lower()
        node = self.cache.reverse_lookup_traced(ip, query)
        if node is None:
            if self.recursion is not None and query.rd():
                query.no_store = True
                return self.recursion.resolve(query)
            query.set_error(Rcode.REFUSED)
            query.stamp("pre-resp")
            query.respond()
            return

        record = node.data if isinstance(node.data, dict) else {}
        rtype = record.get("type")
        sub = record.get(rtype) if isinstance(rtype, str) else None
        ttl = _record_ttl(record, sub if isinstance(sub, dict) else {})
        query.add_answer(PTRRecord(name=domain, ttl=ttl, target=node.domain))
        query.stamp("pre-resp")
        query.respond()
