"""Query-resolution engine — the business logic of the DNS service.

Port of the reference's ``lib/server.js`` ``resolve()`` (:136-429) and
``resolvePtr()`` (:67-134), preserving its deliberate, failover-oriented
rcode policy exactly (SURVEY §7.3 calls these "behaviorally load-bearing"):

- Names outside the DNS domain, invalid names, SRV-shaped names that don't
  parse, and cache misses (without recursion) are **REFUSED**, not
  NXDOMAIN/NODATA, so downstream resolvers fail over to their next
  nameserver instead of erroring out (comment at ``lib/server.js:227-241``).
- The store being unavailable is **SERVFAIL** (``lib/server.js:186-192``).
- An SRV query for a name we own that isn't a service gets NOERROR +
  SOA authority (NODATA with negative-caching TTL, ``lib/server.js:276-292``).
- An SRV query whose service/proto labels don't match the registered ones
  is **NXDOMAIN** (``lib/server.js:334-345``).
- TTL precedence is three-level, deepest-object-wins: default 30s ←
  record.ttl ← record[type].ttl, plus the nested ``service.service`` case
  (``lib/server.js:262-274,326-332``) and min(service-ttl, member-ttl) for
  plain-A service answers (``lib/server.js:403-414``).

Known deviation: the reference's "doubled-up dns domain suffix" REFUSED
check (``lib/server.js:167-175``) is dead code — its ``stripSuffix`` helper
appends ``'...'`` to the stripped name, so the subsequent ``isSuffix`` never
matches.  We implement the evident intent (refuse ``x.foo.com.foo.com`` and
``x.foo.com.<dc>.foo.com``); the externally visible rcode is REFUSED either
way (the reference would miss the cache and refuse too), but we skip the
pointless recursion attempt the reference would make.
"""
from __future__ import annotations

import logging
import random
import re
from typing import Optional
from urllib.parse import urlparse

from binder_tpu.dns.query import QueryCtx
from binder_tpu.dns.wire import (
    ARecord,
    PTRRecord,
    Rcode,
    SOARecord,
    SRVRecord,
    Type,
    ip_from_reverse_name,
)
from binder_tpu.store.cache import MirrorCache
from binder_tpu.store.names import rec_parts as _rec_parts

SRV_RE = re.compile(r"^(_[^_.]*)\.(_[^_.]*)\.(.*)$")
NAME_RE = re.compile(r"[^a-z0-9_.-]")

# Child record types eligible to back a service answer
# (lib/server.js:352-360 — note: plain 'host' and 'db_host' are excluded).
SERVICE_CHILD_TYPES = frozenset({
    "load_balancer", "moray_host", "ops_host", "rr_host", "redis_host",
})

# Record types the engine answers with a single A from the record's own
# address (lib/server.js:306-320) — also exactly the types the compact
# tuple representation fast-paths.
HOST_LIKE_TYPES = frozenset({
    "db_host", "host", "load_balancer", "moray_host", "redis_host",
    "ops_host", "rr_host",
})

DEFAULT_TTL = 30  # reference lib/server.js:270 (the ZK session timeout)


def _is_suffix(suffix: str, s: str) -> bool:
    return s.endswith(suffix)


def _record_ttl(record: dict, sub: dict, default: int = DEFAULT_TTL) -> int:
    """Deepest-object-wins TTL precedence (lib/server.js:262-274)."""
    ttl = default
    if isinstance(record, dict) and record.get("ttl") is not None:
        ttl = record["ttl"]
    if isinstance(sub, dict) and sub.get("ttl") is not None:
        ttl = sub["ttl"]
    return ttl


def _valid_record(record) -> bool:
    """Record must be a dict with a string type and an object sub-record
    (lib/server.js:251-259)."""
    return (isinstance(record, dict)
            and isinstance(record.get("type"), str)
            and isinstance(record.get(record["type"]), dict))


class AnswerPlan:
    """Outcome of PURE resolution for one question — no transport, no
    RD/EDNS posture, no QueryCtx.  The plan/render split exists so the
    same resolution logic serves two callers:

    - the query path (``Resolver.resolve``/``resolve_ptr``): plan, then
      apply to the live QueryCtx (shuffle rotatable groups, respond);
    - the mutation-time precompiler (``resolver/precompile.py``): plan
      once per affected name when the mirror changes, render every
      rotation variant to wire, and install the finished answers so
      post-churn queries never pay a resolve.

    ``groups`` is the rotation unit list: each element is
    ``(answers, additionals)`` for one service member (or the single
    answer for non-service shapes).  The query path shuffles groups
    (round-robin); the precompiler renders cyclic rotations of them.

    Known deviation from the pre-split engine: a service with an
    invalid member record still answers SERVFAIL, but with an empty
    answer section (the old code kept the members it had already
    shuffled past — answer content on SERVFAIL is not load-bearing and
    SERVFAIL is never cached).
    """

    __slots__ = ("rcode", "groups", "authorities", "rotatable",
                 "dep_domain", "miss", "reason", "log_query", "stale")

    def __init__(self) -> None:
        self.rcode = Rcode.NOERROR
        self.groups: list = []        # [(answers, additionals)] per unit
        self.authorities: list = []
        self.rotatable = False
        self.dep_domain: Optional[str] = None
        #: the mirror had no node for the name — the recursion-candidate
        #: shape (rcode is REFUSED; the query path may forward instead)
        self.miss = False
        self.reason: Optional[str] = None      # log_ctx["reason"]
        self.log_query: Optional[dict] = None  # log_ctx["query"]
        #: resolved from a stale mirror (degradation policy: session
        #: down, within maxStalenessSeconds, TTLs clamped)
        self.stale = False

    @property
    def negative(self) -> bool:
        """NXDOMAIN or NODATA (NOERROR with an empty answer section) —
        the shapes the answer cache accounts separately (and SERVFAIL
        is never cached at all)."""
        return (self.rcode == Rcode.NXDOMAIN
                or (self.rcode == Rcode.NOERROR and not self.groups))


class Resolver:
    """Stateless resolution engine over a mirror cache (+ optional
    recursion)."""

    def __init__(self, zk_cache: MirrorCache, dns_domain: str,
                 datacenter_name: str = "",
                 recursion=None,
                 log: Optional[logging.Logger] = None,
                 rng: Optional[random.Random] = None) -> None:
        self.cache = zk_cache
        self.dns_domain = dns_domain.lower() if dns_domain else ""
        self.datacenter_name = datacenter_name
        self.recursion = recursion
        self.log = log or logging.getLogger("binder.resolver")
        self.rng = rng or random.Random()
        # degradation policy engine hooks, assigned by BinderServer
        # (binder_tpu/policy): `policy` gates stale serving (TTL clamp /
        # withhold past the cap), `admission` rate-limits the
        # recursion-triggering shape per client.  None = classic
        # behavior (serve the mirror forever, forward every miss).
        self.policy = None
        self.admission = None

    # -- entry point used by the server engine (lib/server.js:491-506) --
    #
    # Synchronous: cache-served queries complete inline (the hot path);
    # only the recursion handoff returns an awaitable for the caller to
    # drive (cross-DC network I/O).

    def handle(self, query: QueryCtx):
        qt = query.qtype()
        if qt in (Type.A, Type.SRV):
            return self.resolve(query)
        if qt == Type.PTR:
            return self.resolve_ptr(query)
        # anything unsupported we tell the client the truth
        query.set_error(Rcode.NOTIMP)
        query.respond()
        return None

    # -- forward resolution (lib/server.js:136-429) --
    #
    # resolve() = plan() + apply: plan is the PURE resolution (also the
    # mutation-time precompiler's entry point); apply handles the live
    # query's concerns — log context, attribution stamps, the recursion
    # handoff (RD-dependent, so it cannot live in the plan), round-robin
    # shuffle, and the respond.

    def resolve(self, query: QueryCtx):
        plan = self.plan(query.name(), query.qtype())
        return self._finish(query, plan)

    def plan(self, qname: str, qtype: int) -> AnswerPlan:
        """Pure resolution of an A/SRV question against the mirror."""
        p = AnswerPlan()
        domain = qname

        service = protocol = None
        if qtype == Type.SRV:
            m = SRV_RE.match(domain)
            if not m or len(m.group(3)) < 1:
                p.reason = "not a valid SRV lookup domain"
                p.rcode = Rcode.REFUSED
                return p
            service, protocol, domain = m.group(1), m.group(2), m.group(3)

        if self.dns_domain:
            if _is_suffix("." + self.dns_domain, domain):
                stripped = domain[:-(len(self.dns_domain) + 1)]
            else:
                p.reason = "not within dns domain suffix"
                p.rcode = Rcode.REFUSED
                return p
            dcsuff = self.dns_domain + "." + self.datacenter_name
            if (stripped == self.dns_domain
                    or _is_suffix("." + self.dns_domain, stripped)
                    or stripped == dcsuff
                    or _is_suffix("." + dcsuff, stripped)):
                p.reason = "doubled-up dns domain suffix"
                p.rcode = Rcode.REFUSED
                return p

        p.log_query = {
            "srv": f"{service}.{protocol}" if service else None,
            "name": domain,
            "type": Type.name(qtype),
        }

        if not self.cache.is_ready():
            self.log.error("no coordination-store session")
            p.rcode = Rcode.SERVFAIL
            return p

        if len(domain) < 1:
            p.rcode = Rcode.REFUSED
            return p

        domain = domain.lower()
        if NAME_RE.search(domain):
            p.reason = "invalid name"
            p.rcode = Rcode.REFUSED
            return p

        # degradation gate (docs/degradation.md): past the staleness
        # cap the mirror's data may no longer be served at all; within
        # it, answers flow with clamped TTLs (_apply_stale at the
        # positive returns below)
        mode = self._policy_mode()
        if mode == "stale-exhausted":
            return self._withhold(p, domain)
        stale = mode == "stale-serving"

        # dependency tag for the answer caches: whatever this lookup
        # yields (including a miss-REFUSED) changes when `domain`
        # mutates in the store — note for SRV this is the *service node*
        # domain, not the _svc._proto-prefixed qname
        p.dep_domain = domain
        node = self.cache.lookup(domain)

        if node is None:
            # REFUSED, not NXDOMAIN: clients must fail over to their next
            # nameserver (lib/server.js:227-241).  The query path may
            # forward to recursion instead (RD-dependent, see _finish).
            p.miss = True
            p.rcode = Rcode.REFUSED
            return p

        rec = node.rec
        if type(rec) is tuple and rec[0] in HOST_LIKE_TYPES:
            # compact host-like record (store/names.py): the dominant
            # zone shape, resolved without materializing its dict form.
            # Exactly the single-A / SRV-on-non-service outcomes of the
            # generic branch below, same TTL precedence.
            rtype, addr, rttl, rsttl = _rec_parts(rec)
            ttl = rsttl if rsttl is not None else (
                rttl if rttl is not None else DEFAULT_TTL)
            if service is not None:
                # SRV on a non-service name we own: NODATA + SOA for
                # negative caching (lib/server.js:276-292)
                p.authorities.append(SOARecord(
                    name=domain, ttl=ttl, mname=self.dns_domain,
                    minimum=ttl))
                return self._apply_stale(p, stale)
            p.groups.append(([ARecord(name=domain, ttl=ttl,
                                      address=addr)], []))
            return self._apply_stale(p, stale)

        record = node.data
        if not _valid_record(record):
            self.log.error("invalid store record at %s: %r", domain, record)
            p.rcode = Rcode.SERVFAIL
            return p

        sub = record[record["type"]]
        ttl = _record_ttl(record, sub)

        if service is not None and record["type"] != "service":
            # SRV on a non-service name we own: NODATA + SOA for negative
            # caching (lib/server.js:276-292)
            p.authorities.append(SOARecord(
                name=domain, ttl=ttl, mname=self.dns_domain, minimum=ttl))
            return self._apply_stale(p, stale)

        rtype = record["type"]
        if rtype == "database":
            addr = urlparse(sub.get("primary", "")).hostname
            p.groups.append(([ARecord(name=domain, ttl=ttl, address=addr)],
                             []))
        elif rtype in HOST_LIKE_TYPES:
            p.groups.append(([ARecord(name=domain, ttl=ttl,
                                      address=sub.get("address"))], []))
        elif rtype == "service":
            self._plan_service(p, node, record, qname, domain,
                               service, protocol, ttl)
        else:
            self.log.error("record type %r in store is unknown", rtype)
        return self._apply_stale(p, stale)

    # -- degradation-policy plumbing (binder_tpu/policy/degrade.py) --

    def _policy_mode(self) -> str:
        return "fresh" if self.policy is None else self.policy.mode()

    def _withhold(self, p: AnswerPlan, domain: str) -> AnswerPlan:
        """The stale-exhausted shape: the mirror is older than
        maxStalenessSeconds and its data may not be served.  Per
        config: SERVFAIL (clients fail over, the engine's standing
        policy for a broken store) or NODATA + SOA (negative-cacheable
        at the clamp TTL)."""
        pol = self.policy
        pol.note_withheld()
        p.reason = "stale beyond maxStalenessSeconds"
        p.dep_domain = domain
        if pol.exhausted_action == "nodata":
            ttl = pol.stale_ttl_clamp_s
            p.authorities.append(SOARecord(
                name=domain, ttl=ttl, mname=self.dns_domain,
                minimum=ttl))
        else:
            p.rcode = Rcode.SERVFAIL
        return p

    def _apply_stale(self, p: AnswerPlan, stale: bool) -> AnswerPlan:
        """Mark and TTL-clamp a plan resolved from a stale mirror
        (RFC 8767 §5: low TTLs so clients re-ask and converge fast
        after recovery)."""
        if stale:
            clamp = self.policy.stale_ttl_clamp_s
            for answers, additionals in p.groups:
                for rec in answers:
                    rec.ttl = min(rec.ttl, clamp)
                for rec in additionals:
                    rec.ttl = min(rec.ttl, clamp)
            for rec in p.authorities:
                rec.ttl = min(rec.ttl, clamp)
            p.stale = True
            self.policy.note_stale_served()
        return p

    def _plan_service(self, p: AnswerPlan, node, record: dict, qname: str,
                      domain: str, service: Optional[str],
                      protocol: Optional[str], ttl: int) -> None:
        s = record["service"]
        if isinstance(s.get("service"), dict):
            # nested historical format; TTL may live here too
            s = s["service"]
        if s.get("ttl") is not None:
            ttl = s["ttl"]

        if service is not None and (service != s.get("srvce")
                                    or protocol != s.get("proto")):
            # SRV for a service/proto that doesn't match the registered
            # one: we own the name, so NXDOMAIN (lib/server.js:334-345)
            p.rcode = Rcode.NXDOMAIN
            return

        # explicit NOERROR so an empty service doesn't fall through
        # (lib/server.js:347-351)
        p.rcode = Rcode.NOERROR

        kids = []
        for k in node.children:
            kr = k.rec
            if type(kr) is tuple:
                if kr[0] in SERVICE_CHILD_TYPES:
                    kids.append(k)
            elif isinstance(kr, dict) \
                    and kr.get("type") in SERVICE_CHILD_TYPES:
                kids.append(k)

        for knode in kids:
            kr = knode.rec
            if type(kr) is tuple:
                # compact member: address always present, no ports key
                _kt, addr, kttl, ksttl = _rec_parts(kr)
                ports = [s.get("port")]
                rttl = ksttl if ksttl is not None else (
                    kttl if kttl is not None else ttl)
            else:
                krec = kr
                if not _valid_record(krec):
                    p.rcode = Rcode.SERVFAIL
                    p.groups = []
                    self.log.error("bad store info under %s", domain)
                    return
                ksub = krec[krec["type"]]
                addr = ksub.get("address")
                if addr is None:
                    continue
                ports = ksub.get("ports")
                if not ports:
                    ports = [s.get("port")]
                rttl = _record_ttl(krec, ksub, ttl)

            if service is not None:
                nm = f"{knode.name}.{domain}"
                answers = [SRVRecord(
                    name=qname, ttl=ttl, priority=0, weight=10,
                    port=prt, target=nm) for prt in ports]
                p.groups.append(
                    (answers, [ARecord(name=nm, ttl=rttl, address=addr)]))
            else:
                # plain A for a service: membership AND address — use the
                # smaller of the two TTLs (lib/server.js:403-414)
                p.groups.append(([ARecord(name=domain, ttl=min(ttl, rttl),
                                          address=addr)], []))
        p.rotatable = len(p.groups) > 1

    def _finish(self, query: QueryCtx, plan: AnswerPlan):
        """Apply a plan to a live query: log context, the store-lookup
        attribution stamp, the RD-dependent recursion handoff, group
        shuffle (round-robin), and the respond."""
        if plan.log_query is not None:
            query.log_ctx["query"] = plan.log_query
        if plan.reason is not None:
            query.log_ctx["reason"] = plan.reason
        if plan.dep_domain is not None:
            query.dep_domain = plan.dep_domain
        if plan.stale:
            query.log_ctx["stale"] = True
        # decode→policy→mirror probe→plan, on the attribution timeline
        query.stamp("store-lookup")
        if plan.miss and self.recursion is not None and query.rd():
            adm = self.admission
            if adm is not None and not adm.allow_recursion(query.src[0]):
                # recursion-triggering floods are shed per client
                # BEFORE any upstream work (docs/degradation.md):
                # well-formed REFUSED, clients fail over.  The shed is
                # a PER-CLIENT transient — it must never enter the
                # shared answer cache, or one client's flood poisons
                # the name with REFUSED for everyone until expiry
                query.no_store = True
                query.set_error(Rcode.REFUSED)
                query.log_ctx["reason"] = "recursion rate limit"
                query.stamp("pre-resp")
                query.respond()
                return None
            # recursion answers belong to another DC's store — no
            # cache layer may keep them (query.no_store reaches the
            # balancer as the do-not-store transport marker)
            query.no_store = True
            return self.recursion.resolve(query)
        query.set_error(plan.rcode)
        groups = plan.groups
        if plan.rotatable:
            groups = list(groups)
            self.rng.shuffle(groups)
        for answers, additionals in groups:
            for rec in answers:
                query.add_answer(rec)
            for rec in additionals:
                query.add_additional(rec)
        for rec in plan.authorities:
            query.add_authority(rec)
        query.stamp("pre-resp")
        query.respond()

    # -- reverse resolution (lib/server.js:67-134) --

    def resolve_ptr(self, query: QueryCtx):
        plan = self.plan_ptr(query.name())
        return self._finish(query, plan)

    def plan_ptr(self, qname: str) -> AnswerPlan:
        """Pure resolution of a PTR question against the reverse map."""
        p = AnswerPlan()
        parts = list(reversed(qname.split(".")))
        if len(parts) >= 2 and parts[0] == "arpa" and parts[1] == "ip6":
            # IPv6 reverse: strict canonical nibble parse — the reverse
            # map is keyed by the canonical address string, and a
            # malformed ip6.arpa name simply misses (REFUSED below)
            ip = ip_from_reverse_name(qname.lower())
            if ip is None:
                p.reason = "not a valid ip6 reverse name"
                p.rcode = Rcode.REFUSED
                return p
        elif (len(parts) < 2 or parts[0] != "arpa"
                or parts[1] != "in-addr"):
            p.reason = "not an ipv4 reverse name"
            p.rcode = Rcode.REFUSED
            return p
        else:
            # No octet validation: an invalid address simply misses the
            # cache and is REFUSED, so the client tries its next NS
            # (comment at lib/server.js:79-83)
            ip = ".".join(parts[2:])

        if not self.cache.is_ready():
            self.log.error("no coordination-store session")
            p.rcode = Rcode.SERVFAIL
            return p

        p.log_query = {"ip": ip, "type": Type.name(Type.PTR)}

        # degradation gate, same policy as the forward tree
        mode = self._policy_mode()
        if mode == "stale-exhausted":
            return self._withhold(p, qname.lower())

        # dependency tag: mutations touching this address emit the
        # normalized reverse qname (store/cache.py _rev_name)
        p.dep_domain = qname.lower()
        node = self.cache.reverse_lookup(ip)
        if node is None:
            p.miss = True
            p.rcode = Rcode.REFUSED
            return p

        rec = node.rec
        if type(rec) is tuple:
            _rt, _addr, rttl, rsttl = _rec_parts(rec)
            ttl = rsttl if rsttl is not None else (
                rttl if rttl is not None else DEFAULT_TTL)
        else:
            record = rec if isinstance(rec, dict) else {}
            rtype = record.get("type")
            sub = record.get(rtype) if isinstance(rtype, str) else None
            ttl = _record_ttl(record, sub if isinstance(sub, dict) else {})
        p.groups.append(([PTRRecord(name=qname, ttl=ttl,
                                    target=node.domain)], []))
        return self._apply_stale(p, mode == "stale-serving")
