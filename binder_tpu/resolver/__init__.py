"""Resolution engine (port of the reference's lib/server.js logic)."""
from binder_tpu.resolver.engine import (  # noqa: F401
    DEFAULT_TTL,
    AnswerPlan,
    Resolver,
    SERVICE_CHILD_TYPES,
)
from binder_tpu.resolver.precompile import Precompiler  # noqa: F401
