"""Mutation-time answer precompilation: the miss path at hit speed.

The r05 bench put the shape of the problem on the table: answer-cache
hits serve ~347k qps, but anything that reaches the resolver engine
collapses ~10x, and churn — which invalidates cached answers and forces
re-resolution — drags the fronted rate with it.  The reference binder
has the same resolve-per-miss shape over its ZK mirror.  This module
moves that work from query time to mutation time, the incremental-
computation approach Janus (arXiv:2511.02559) applies to DNS and
ZDNS-style wire-speed encoding (arXiv:2309.13495) makes cheap per
record:

- when the mirror applies a mutation (``MirrorCache.invalidate`` →
  ``BinderServer._on_store_invalidate``), the answers the invalidation
  actually DROPPED — the shapes with serving evidence, including
  concrete negative qnames clients asked — are eagerly re-resolved
  (``Resolver.plan`` — pure resolution, no QueryCtx) and re-rendered to
  wire: every round-robin rotation variant, SRV answer+additional
  sections, negative answers (NXDOMAIN / NODATA+SOA), in both EDNS
  postures.  Mutations of names nobody queries cost nothing beyond the
  synchronous drop;
- at startup the whole mirror is seeded (``seed_mirror`` — the
  ``_zone_fill`` analog), including into the native answer cache under
  the canonical client postures, so a cold zone serves precompiled from
  query one;
- the finished wires are installed into the ``AnswerCache``'s compiled
  table under the same dependency tags, so the post-churn query is a
  dict probe plus an ID/flags patch (``dns/wire.patch_answer_wire``)
  instead of an ``engine.resolve()`` pass;
- the work rides a bounded, coalescing queue drained in batches between
  event-loop passes.  A watch storm that outruns the queue SHEDS the
  overflow — those names simply degrade to today's lazy re-resolution —
  with a ``precompile-shed`` flight-recorder event and the
  ``binder_precompile_*`` metrics keeping the evidence.  The serving
  loop can never be stalled by refill work (the drops that guarantee
  coherence are synchronous in the server and are not this module's
  concern).

What never gets compiled: SERVFAIL (store down / garbage record — must
re-check per query, and the cache-never rule is absolute), and
miss-REFUSED when recursion is configured (the answer is RD-dependent
there; the lazy path owns the split).
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Iterable, Optional, Tuple

from binder_tpu.dns.wire import (
    Message,
    OPTRecord,
    Question,
    Rcode,
    Type,
    WireError,
)

#: the EDNS echo appended to every EDNS response (identical instance
#: semantics to QueryCtx._ECHO_OPT: payload ceiling 1232, at the HEAD of
#: the additionals section, before any answer-derived additionals)
_ECHO_OPT = OPTRecord(name="", ttl=0, udp_payload_size=1232)

#: its wire form — byte-identical to OPTRecord.encode's output (pinned
#: by the byte-parity tests): root name, TYPE OPT(41), CLASS=1232,
#: TTL 0, RDLEN 0
_ECHO_OPT_WIRE = b"\x00\x00\x29\x04\xd0\x00\x00\x00\x00\x00\x00"

#: a work item is one question identity
Item = Tuple[int, str]   # (qtype, qname)


class Precompiler:
    #: items compiled per event-loop pass — the FLOOR; the drain keeps
    #: going past it only while the time budget below lasts, so backlog
    #: drain rate scales with how cheap the renders actually are
    #: instead of a fixed count guessing at it
    BATCH = 64
    #: hard per-pass ceiling (a pass of pathologically cheap items must
    #: still yield the loop)
    MAX_BATCH = 512
    #: per-pass wall budget: refill work between serving batches stays
    #: well under the loop-lag watchdog threshold even at zone scale
    DRAIN_BUDGET_S = 0.002
    #: queue bound FLOOR; the effective bound scales with the mirrored
    #: zone (``_max_pending``) so a large zone's legitimate churn burst
    #: is not shed at a toy zone's threshold, while staying hard-capped
    MAX_PENDING = 2048
    MAX_PENDING_CAP = 65536
    #: rotation variants rendered per rotatable answer set, in lockstep
    #: with AnswerCache.variants_cap / the native FP_MAX_VARIANTS
    VARIANTS_CAP = 8
    #: answer-set size ceiling: a service with hundreds of members
    #: renders VARIANTS_CAP full rotations of the whole set — one such
    #: item can cost hundreds of ms (a measured 300 ms loop stall at
    #: zone scale), and its wire exceeds every UDP payload so the
    #: compiled entry could never serve UDP anyway.  Oversize sets stay
    #: lazy (the engine serves them, with TC -> TCP as usual).
    MAX_SET_RECORDS = 64
    #: shed flight-recorder events are rate-limited to one per window
    SHED_EVENT_WINDOW_S = 1.0
    #: zones at or below this seed inline at startup (the historical
    #: behavior every small-zone test relies on); larger mirrors seed
    #: from a chunked background task so a million-name zone starts
    #: serving immediately and fills in behind the traffic
    SEED_INLINE_MAX = 20000

    def __init__(self, *, resolver, answer_cache, zk_cache, summarize,
                 collector=None, recorder=None,
                 log: Optional[logging.Logger] = None,
                 native_put=None, tracer=None) -> None:
        self.resolver = resolver
        self.answer_cache = answer_cache
        self.zk_cache = zk_cache
        self.summarize = summarize        # BinderServer._summarize
        # optional native-tier install hook
        # (BinderServer._precompile_native_put): compiled answers land
        # in the C answer cache too, under the canonical client
        # postures, so the post-churn miss path is LITERALLY the native
        # hit path
        self.native_put = native_put
        self.recorder = recorder
        self.log = log or logging.getLogger("binder.precompile")
        # optional propagation tracer (binder_tpu/verify): each queued
        # item remembers the mutation trace context that enqueued it,
        # so the async re-render reports against the mutation's t0
        self.tracer = tracer
        self._pending_trace: dict = {}
        # insertion-ordered set of pending items (dict keys)
        self._pending: dict = {}
        self._drain_scheduled = False
        # chunked startup seed (large zones only)
        self._seed_task = None
        self._seed_remaining = 0
        # monotonic counters (also folded into the metrics below)
        self.compiled = 0
        self.declined = 0
        self.shed = 0
        self._shed_event_last = 0.0
        self._m_compiled = self._m_declined = self._m_shed = None
        if collector is not None:
            self._m_compiled = collector.counter(
                "binder_precompile_compiled",
                "answers re-rendered and installed at mutation time"
            ).labelled()
            self._m_declined = collector.counter(
                "binder_precompile_declined",
                "precompile work items declined to lazy resolution "
                "(SERVFAIL shapes, recursion-dependent misses, encode "
                "failures)").labelled()
            self._m_shed = collector.counter(
                "binder_precompile_shed",
                "precompile work items shed under queue pressure "
                "(watch storms degrade to lazy resolution)").labelled()
            collector.gauge(
                "binder_precompile_queue_depth",
                "precompile work items awaiting re-render"
            ).set_function(lambda: float(len(self._pending)))
            # materialize every series at 0: shedding evidence must be
            # scrapeable (and rate()-able) before the first shed, and
            # the validator pins the full family's presence
            for child in (self._m_compiled, self._m_declined,
                          self._m_shed):
                child.inc(0)

    # -- work intake --

    #: forward record types worth an eager render — exactly the shapes
    #: the resolver answers positively (engine.plan's type dispatch)
    _RENDERABLE_TYPES = frozenset({
        "db_host", "host", "load_balancer", "moray_host", "redis_host",
        "ops_host", "rr_host", "database", "service",
    })

    def items_for_tag(self, tag: str) -> Iterable[Item]:
        """The question identities a dependency tag's mutation may have
        changed AND can serve something: the PTR shape for reverse tags
        that currently map to an owner; the A shape for forward tags
        whose node resolves to an answerable record, plus — for service
        nodes with a registered srvce/proto — the SRV qname.

        Used by the STARTUP SEED walk only — the mutation path
        re-renders from the dropped-key evidence instead (see
        ``enqueue``)."""
        if tag.endswith(".in-addr.arpa"):
            parts = tag.split(".")
            if len(parts) >= 3:
                ip = ".".join(reversed(parts[:-2]))
                if self.zk_cache.reverse_lookup(ip) is not None:
                    yield (Type.PTR, tag)
            return
        node = self.zk_cache.lookup(tag)
        record = node.data if node is not None else None
        if not (isinstance(record, dict)
                and record.get("type") in self._RENDERABLE_TYPES):
            return
        yield (Type.A, tag)
        if record.get("type") != "service":
            return
        s = record.get("service")
        if isinstance(s, dict) and isinstance(s.get("service"), dict):
            s = s["service"]            # nested historical format
        if not isinstance(s, dict):
            return
        srvce, proto = s.get("srvce"), s.get("proto")
        if isinstance(srvce, str) and isinstance(proto, str) \
                and srvce and proto:
            yield (Type.SRV, f"{srvce}.{proto}.{tag}".lower())

    def enqueue(self, items) -> None:
        """Queue re-renders for a mutation event.  ``items`` is the
        invalidation's dropped-key list — ``(qtype, qname,
        evidence_at)`` triples for the question shapes that were
        actually BEING SERVED when the mutation killed them: per-key
        entries (a query created them) and compiled entries whose query
        evidence is still inside the expiry window.  Churn on names
        nobody queries therefore costs the precompiler nothing
        (measured: eager re-render of every mutated name taxed hot-mix
        churn throughput ~15% on a 1-core box, all of it spent on
        answers no one asked for), while a hot name's answers are
        re-rendered the moment its mutation lands.  Coalescing is by
        question identity — a name mutated ten times in one burst is
        rendered once, under its freshest evidence."""
        pending = self._pending
        room = self._max_pending() - len(pending)
        shed = 0
        tracer = self.tracer
        ctx = tracer.current if tracer is not None else None
        for qtype, qname, evidence_at in items:
            key = (qtype, qname)
            have = pending.get(key)
            if have is not None:
                if evidence_at > have:
                    pending[key] = evidence_at
                if ctx is not None:
                    self._pending_trace[key] = ctx
                continue                # coalesced
            if room <= 0:
                shed += 1
                continue
            pending[key] = evidence_at
            if ctx is not None:
                self._pending_trace[key] = ctx
            room -= 1
        if shed:
            self._note_shed(shed)
        self._schedule()

    def _note_shed(self, shed: int) -> None:
        self.shed += shed
        if self._m_shed is not None:
            self._m_shed.inc(shed)
        now = time.monotonic()
        if (self.recorder is not None
                and now - self._shed_event_last >= self.SHED_EVENT_WINDOW_S):
            self._shed_event_last = now
            self.recorder.record(
                "precompile-shed", shed=shed, pending=len(self._pending),
                max_pending=self._max_pending())

    def _max_pending(self) -> int:
        """Scale-aware queue bound: at least MAX_PENDING, growing with
        the mirrored zone up to the hard cap.  A 100-name test zone
        sheds exactly where it always did; a million-name zone's watch
        storm gets a proportionate buffer before degrading to lazy."""
        return max(self.MAX_PENDING,
                   min(len(self.zk_cache.nodes), self.MAX_PENDING_CAP))

    # -- the bounded drain --

    def _schedule(self) -> None:
        if self._drain_scheduled or not self._pending:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # no loop (synchronous setup paths, tests against the fake
            # store): compile inline — there is no serving loop to stall
            while self._pending:
                item, ev, trace = self._pop()
                self._compile_one(item, evidence_at=ev, trace=trace)
            return
        self._drain_scheduled = True
        loop.call_soon(self._drain)

    def _pop(self):
        item = next(iter(self._pending))
        return (item, self._pending.pop(item),
                self._pending_trace.pop(item, None))

    def _drain(self) -> None:
        self._drain_scheduled = False
        n = 0
        t0 = time.perf_counter()
        while self._pending and n < self.MAX_BATCH:
            item, ev, trace = self._pop()
            try:
                self._compile_one(item, evidence_at=ev, trace=trace)
            except Exception:  # noqa: BLE001 — see below
                # precompilation is an optimization: a render bug must
                # never break the mutation path that feeds it
                self.log.exception("precompile failed for %s", item)
                self._decline()
            n += 1
            if (n >= self.BATCH
                    and time.perf_counter() - t0 >= self.DRAIN_BUDGET_S):
                break
        if self._pending:
            # more pending: yield to I/O first (call_soon callbacks
            # added during a loop pass run on the NEXT pass)
            self._schedule()

    def seed_mirror(self) -> None:
        """Compile every currently mirrored name — run once at server
        start, for mirrors built before this server subscribed to
        invalidation events (the same reason ``_zone_fill`` exists).
        Later arrivals ride the mutation path.

        Small zones seed inline (the historical semantics: precompiled
        from query one).  Past ``SEED_INLINE_MAX`` the walk moves to a
        time-budgeted background task — a million-name zone must start
        SERVING immediately; unseeded names resolve lazily until their
        chunk lands (scale-aware backpressure, ISSUE 7)."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is None \
                or len(self.zk_cache.nodes) <= self.SEED_INLINE_MAX:
            for domain in list(self.zk_cache.nodes):
                self._seed_one(domain)
            return
        self._seed_task = loop.create_task(self._seed_chunked())

    def _seed_one(self, domain: str) -> None:
        node = self.zk_cache.nodes.get(domain)
        if node is None:
            return                      # left the mirror mid-walk
        for item in self.items_for_tag(domain):
            try:
                self._compile_one(item, native=True)
            except Exception:
                self.log.exception("precompile seed failed for %s", item)
        ip = getattr(node, "ip", None)
        if ip and type(ip) is str:
            parts = ip.split(".")
            if len(parts) == 4 and all(p.isdigit() for p in parts):
                rev = ".".join(reversed(parts)) + ".in-addr.arpa"
                try:
                    self._compile_one((Type.PTR, rev), native=True)
                except Exception:
                    self.log.exception(
                        "precompile seed failed for %s", rev)

    async def _seed_chunked(self) -> None:
        domains = list(self.zk_cache.nodes)
        self._seed_remaining = len(domains)
        self.log.info("precompile seed: %d names, chunked", len(domains))
        started = time.perf_counter()
        i = 0
        while i < len(domains):
            t0 = time.perf_counter()
            while i < len(domains) \
                    and time.perf_counter() - t0 < self.DRAIN_BUDGET_S:
                self._seed_one(domains[i])
                i += 1
            self._seed_remaining = len(domains) - i
            await asyncio.sleep(0)
        self.log.info("precompile seed done: %d names in %.1fs",
                      len(domains), time.perf_counter() - started)

    # -- one item: plan → render variants → install --

    def _decline(self) -> None:
        self.declined += 1
        if self._m_declined is not None:
            self._m_declined.inc()

    def render_variants(self, qname: str, qtype: int, plan):
        """The full rotation-variant set for *plan*: ``(w0, w1,
        answers_summary, additionals_summary)`` per variant, in the
        deterministic rotation order — or None when the set is
        oversize or unencodable (those shapes stay lazy).  Shared with
        the verify layer's compiled-bytes check, which re-renders and
        compares byte-for-byte (``verify/checker.py``)."""
        groups = plan.groups
        if sum(len(g[0]) + len(g[1]) for g in groups) \
                > self.MAX_SET_RECORDS:
            return None                 # oversize answer set: lazy
        nv = min(len(groups), self.VARIANTS_CAP) if plan.rotatable else 1
        variants = []
        summarize = self.summarize
        try:
            for i in range(nv):
                rot = groups[i:] + groups[:i]
                answers = [r for g in rot for r in g[0]]
                adds = [r for g in rot for r in g[1]]
                w0 = self._render(qname, qtype, plan, answers, adds,
                                  False)
                if adds:
                    # answer-derived additionals sit AFTER the OPT echo
                    # (QueryCtx appends the echo at construction): the
                    # EDNS posture needs its own full encode
                    w1 = self._render(qname, qtype, plan, answers,
                                      adds, True)
                else:
                    # no additionals: the EDNS wire is the bare wire
                    # plus the echo OPT at the tail, arcount 0 -> 1 —
                    # half the encode cost on the dominant (host A,
                    # PTR, negative) mutation shapes
                    w1 = (w0[:10] + b"\x00\x01" + w0[12:]
                          + _ECHO_OPT_WIRE)
                variants.append((
                    w0, w1,
                    [summarize(r) for r in answers],
                    [summarize(r) for r in adds],
                ))
        except WireError:
            return None                 # unencodable store value: lazy
        return variants

    def _compile_one(self, item: Item, native: bool = False,
                     evidence_at: Optional[float] = None,
                     trace=None) -> None:
        """``native=True`` only on the startup seed: the C answer cache
        is COLD there, so installing the whole mirror is pure win.  The
        mutation path must NOT native-install — its sustained insert
        stream would evict the resident hot set (the C cache evicts
        oldest-inserted within a probe window), which measured as a
        ~45%% churn-throughput collapse.  Post-churn names serve from
        the Python compiled table immediately and re-enter the native
        tier through the ordinary promote-on-first-hit path once they
        prove hot.  ``evidence_at`` propagates the shape's query
        evidence (see AnswerCache.put_compiled); None on the seed.
        ``trace`` is the enqueueing mutation's propagation-trace
        context (verify/tracer.py), None outside the mutation path."""
        qtype, qname = item
        epoch = self.zk_cache.epoch
        if qtype == Type.PTR:
            plan = self.resolver.plan_ptr(qname)
        else:
            plan = self.resolver.plan(qname, qtype)
        if plan.rcode == Rcode.SERVFAIL:
            self._decline()             # never cache SERVFAIL
            return
        if plan.miss:
            # nothing to serve: with recursion the answer is
            # RD-dependent (REFUSED vs cross-DC forward) and only the
            # lazy path may decide; without it, eagerly re-rendering
            # REFUSED for every name that ever existed is unbounded
            # churn amplification (the old-address PTR shape arrives
            # here on EVERY rewrite).  Misses stay lazy — the per-key
            # cache absorbs any repeat, as it always has.
            self._decline()
            return
        variants = self.render_variants(qname, qtype, plan)
        if variants is None:
            self._decline()
            return
        if trace is not None and self.tracer is not None:
            self.tracer.observe("precompile-render", trace)
        tag = plan.dep_domain or qname
        self.answer_cache.put_compiled(
            qtype, qname, epoch, variants, rotatable=plan.rotatable,
            tag=tag, negative=plan.negative, evidence_at=evidence_at)
        if trace is not None and self.tracer is not None:
            self.tracer.observe("compiled-install", trace)
        if native and self.native_put is not None:
            self.native_put(qtype, qname, variants, tag, plan.rcode)
        self.compiled += 1
        if self._m_compiled is not None:
            self._m_compiled.inc()

    @staticmethod
    def _render(qname: str, qtype: int, plan, answers, adds,
                edns: bool) -> bytes:
        """One canonical response wire (id 0, RD clear) — byte-identical
        to what ``QueryCtx.respond`` encodes for this plan, because it
        IS the same ``Message.encode``: qr/aa set, the EDNS echo (when
        present) at the head of the additionals, full name
        compression."""
        msg = Message(
            id=0, qr=True, aa=True, rd=False, rcode=plan.rcode,
            questions=[Question(name=qname, qtype=qtype)],
            answers=list(answers),
            authorities=list(plan.authorities),
            additionals=([_ECHO_OPT] + list(adds)) if edns
            else list(adds))
        return msg.encode()

    # -- introspection (status.py `precompile` section) --

    def introspect(self) -> dict:
        return {
            "queue_depth": len(self._pending),
            "max_pending": self._max_pending(),
            "batch": self.BATCH,
            "compiled": self.compiled,
            "declined": self.declined,
            "shed": self.shed,
            "seed_remaining": self._seed_remaining,
        }
