"""Encoded-answer cache with per-name (tag) invalidation.

The modern incarnation of the reference's legacy cache flags (``-s size``
default 10000, ``-a expiry`` default 60000 ms — reference
``main.js:34-38``, ``README.md:40-44``): resolvers re-ask the same handful
of names continuously, so the fully-encoded response bytes are cached, keyed
on the decoded fields the response depends on (transport semantics,
RD, question, EDNS presence/payload — see ``BinderServer._on_query``;
raw-wire keying would let per-packet EDNS options mint unbounded keys).
Stored values are opaque to this class — the server stores ``(wire,
answers_summary, additional_summary)`` tuples so cache hits keep full
query-log detail.

Correctness properties:
- every entry records the mirror cache's *epoch* (bumped on full
  rebuilds/session events), so a hit can never survive a re-mirror;
- every entry carries a *dependency tag* — the store lookup domain (or
  PTR qname) its answer derives from; a mirrored mutation invalidates
  exactly the tags it touched (``MirrorCache.invalidate``), so one
  churning record no longer evicts every cached answer;
- round-robin is preserved: each miss stores another shuffle variant (up
  to ``variants_cap``), and hits cycle through the collected variants;
- entries expire after ``expiry_ms`` regardless (defense in depth);
- SERVFAIL and recursion-produced responses are never cached (the callers
  decide; see ``BinderServer._on_query``).
"""
from __future__ import annotations

import time
from typing import Dict, Optional, Set


class AnswerCache:
    __slots__ = ("size", "expiry_s", "variants_cap", "_entries",
                 "_by_tag", "hits", "misses", "invalidations")

    def __init__(self, size: int = 10000, expiry_ms: int = 60000,
                 variants_cap: int = 8) -> None:
        self.size = size
        self.expiry_s = expiry_ms / 1000.0
        self.variants_cap = variants_cap
        # key -> [epoch, created, next_variant_idx, [value, ...],
        #         complete, tag, pushed]
        self._entries: Dict[object, list] = {}
        # dependency tag -> keys whose answers derive from it
        self._by_tag: Dict[str, Set[object]] = {}
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    def _drop(self, key, e) -> None:
        del self._entries[key]
        tag = e[5]
        keys = self._by_tag.get(tag)
        if keys is not None:
            keys.discard(key)
            if not keys:
                del self._by_tag[tag]

    def get(self, key, epoch: int) -> Optional[object]:
        if self.size <= 0:
            return None
        e = self._entries.get(key)
        if e is None:
            self.misses += 1
            return None
        if e[0] != epoch or time.monotonic() - e[1] > self.expiry_s:
            self._drop(key, e)
            self.misses += 1
            return None
        variants = e[3]
        if not e[4] and len(variants) < self.variants_cap:
            # rotatable answer set: keep resolving until we've collected
            # enough shuffle variants for fair rotation
            self.misses += 1
            return None
        idx = e[2]
        e[2] = (idx + 1) % len(variants)
        self.hits += 1
        return variants[idx]

    def put(self, key, epoch: int, value: object,
            rotatable: bool = False, tag: Optional[str] = None) -> bool:
        """Record a freshly resolved value.  ``tag`` is the store name
        the answer depends on (defaults handled by the caller).  Returns
        True exactly when the entry just became *complete*
        (non-rotatable, or the full variant set collected) — the signal
        the server uses to push the entry to the native fast path (see
        BinderServer._on_query)."""
        if self.size <= 0:
            return False
        e = self._entries.get(key)
        if e is not None and e[0] == epoch:
            if len(e[3]) < self.variants_cap:
                e[3].append(value)
                return not e[4] and len(e[3]) == self.variants_cap
            return False
        if e is not None:
            self._drop(key, e)          # stale epoch: replace cleanly
        if len(self._entries) >= self.size:
            # evict oldest insertion (dicts preserve insertion order)
            old_key = next(iter(self._entries))
            self._drop(old_key, self._entries[old_key])
        self._entries[key] = [epoch, time.monotonic(), 0, [value],
                              not rotatable, tag, False]
        self._by_tag.setdefault(tag, set()).add(key)
        return not rotatable

    def take_push(self, key, epoch: int):
        """Claim a complete entry for promotion to the native fast
        path: returns ``(variant_values, tag)`` exactly once (marking
        the entry pushed), else None.  Promotion happens on an entry's
        FIRST HIT, not at resolve time — one-shot names (the cache-cold
        workload) then never pay the native push cost, while any name
        asked twice is native from its third query on."""
        e = self._entries.get(key)
        if e is None or e[0] != epoch or e[6] or not (
                e[4] or len(e[3]) >= self.variants_cap):
            return None
        e[6] = True
        return e[3], e[5]

    def invalidate_tag(self, tag: str) -> int:
        """Drop every entry whose answer derives from ``tag``; returns
        how many were dropped."""
        keys = self._by_tag.pop(tag, None)
        if not keys:
            return 0
        n = 0
        for key in keys:
            if self._entries.pop(key, None) is not None:
                n += 1
        self.invalidations += n
        return n

    def remaining_ttl_ms(self, key, epoch: int) -> Optional[float]:
        """Milliseconds until this entry's time expiry — a late-completed
        rotatable entry must carry its *remaining* lifetime into the
        native fast path, not a fresh full window."""
        e = self._entries.get(key)
        if e is None or e[0] != epoch:
            return None
        return max(0.0, (self.expiry_s - (time.monotonic() - e[1]))
                   * 1000.0)

    def stats(self) -> dict:
        """Occupancy + economics for the introspection snapshot
        (binder_tpu/introspect/status.py `answer_cache` section)."""
        hits, misses = self.hits, self.misses
        total = hits + misses
        return {
            "size": self.size,
            "entries": len(self._entries),
            "hits": hits,
            "misses": misses,
            "hit_ratio": (hits / total) if total else 0.0,
            "invalidations": self.invalidations,
            "expiry_ms": self.expiry_s * 1000.0,
        }

    def clear(self) -> None:
        self._entries.clear()
        self._by_tag.clear()
