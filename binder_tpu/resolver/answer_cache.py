"""Encoded-answer cache with per-name (tag) invalidation.

The modern incarnation of the reference's legacy cache flags (``-s size``
default 10000, ``-a expiry`` default 60000 ms — reference
``main.js:34-38``, ``README.md:40-44``): resolvers re-ask the same handful
of names continuously, so the fully-encoded response bytes are cached, keyed
on the decoded fields the response depends on (transport semantics,
RD, question, EDNS presence/payload — see ``BinderServer._on_query``;
raw-wire keying would let per-packet EDNS options mint unbounded keys).
Stored values are opaque to this class — the server stores ``(wire,
answers_summary, additional_summary)`` tuples so cache hits keep full
query-log detail.

Correctness properties:
- every entry records the mirror cache's *epoch* (bumped on full
  rebuilds/session events), so a hit can never survive a re-mirror;
- every entry carries a *dependency tag* — the store lookup domain (or
  PTR qname) its answer derives from; a mirrored mutation invalidates
  exactly the tags it touched (``MirrorCache.invalidate``), so one
  churning record no longer evicts every cached answer;
- round-robin is preserved: each miss stores another shuffle variant (up
  to ``variants_cap``), and hits cycle through the collected variants;
- entries expire after ``expiry_ms`` regardless (defense in depth);
- negative answers (NXDOMAIN, and NODATA — NOERROR with no answers) are
  cached like positives but accounted separately (``negative`` flag,
  ``neg_entries``/``neg_hits`` in ``stats()``), so a miss flood of
  nonexistent names is visibly absorbed here instead of hitting the
  resolver engine;
- SERVFAIL and recursion-produced responses are NEVER cached (the callers
  decide; see ``BinderServer._on_query`` — SERVFAIL means the store is
  unavailable or a record is garbage, conditions that must re-check on
  every query).

The **compiled-answer table** (``put_compiled``/``get_compiled``) is the
mutation-time precompiler's install target (``resolver/precompile.py``):
one entry per ``(qtype, qname)``, holding every rotation variant in both
EDNS postures, probed by the serve paths on a per-key miss.  Compiled
entries share the tag index — ``invalidate_tag`` drops them in the same
pass — and the epoch check, but do NOT time-expire: their staleness is
bounded by tag invalidation + the epoch (every change that could affect
them arrives as one or the other), and the table is size-bounded by
insertion-order eviction like the per-key side.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Set, Tuple

from binder_tpu.store import names as _names

#: sentinel marking compiled-table keys inside the shared tag index
_COMPILED = object()


class AnswerCache:
    __slots__ = ("size", "compiled_size", "expiry_s", "variants_cap",
                 "_entries", "_compiled", "_by_tag", "hits", "misses",
                 "invalidations", "neg_hits", "compiled_serves",
                 "compiled_installs", "_intern")

    def __init__(self, size: int = 10000, expiry_ms: int = 60000,
                 variants_cap: int = 8,
                 compiled_size: Optional[int] = None,
                 intern=None) -> None:
        # canonicalizer for tag/qname strings entering the long-lived
        # indexes: query-decoded names dedup against the mirror's own
        # domain objects (MirrorCache.canon) or the process-wide pool,
        # so a name is ONE object no matter how many layers index it
        self._intern = intern if intern is not None \
            else _names.intern_name
        self.size = size
        #: compiled-table occupancy bound; defaults to the per-key size
        #: (entries derive 1:1-ish from mirrored names, so operators with
        #: a large zone raise it with the ``precompileSize`` config key)
        self.compiled_size = size if compiled_size is None else compiled_size
        self.expiry_s = expiry_ms / 1000.0
        self.variants_cap = variants_cap
        # key -> [epoch, created, next_variant_idx, [value, ...],
        #         complete, tag, pushed, negative, qkey]
        self._entries: Dict[object, list] = {}
        # (qtype, qname) -> [epoch, next_variant_idx, variants, rotatable,
        #                    tag, negative]
        self._compiled: Dict[Tuple[int, str], list] = {}
        # dependency tag -> keys whose answers derive from it (per-key
        # keys verbatim; compiled keys wrapped as (_COMPILED, qtype, name))
        self._by_tag: Dict[str, Set[object]] = {}
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.neg_hits = 0
        self.compiled_serves = 0
        self.compiled_installs = 0

    def _drop(self, key, e) -> None:
        del self._entries[key]
        self._drop_tag(e[5], key)

    def _drop_tag(self, tag, tag_key) -> None:
        keys = self._by_tag.get(tag)
        if keys is not None:
            keys.discard(tag_key)
            if not keys:
                del self._by_tag[tag]

    def get(self, key, epoch: int) -> Optional[object]:
        if self.size <= 0:
            return None
        e = self._entries.get(key)
        if e is None:
            self.misses += 1
            return None
        if e[0] != epoch or time.monotonic() - e[1] > self.expiry_s:
            self._drop(key, e)
            self.misses += 1
            return None
        variants = e[3]
        if not e[4] and len(variants) < self.variants_cap:
            # rotatable answer set: keep resolving until we've collected
            # enough shuffle variants for fair rotation
            self.misses += 1
            return None
        idx = e[2]
        e[2] = (idx + 1) % len(variants)
        self.hits += 1
        if e[7]:
            self.neg_hits += 1
        return variants[idx]

    def put(self, key, epoch: int, value: object,
            rotatable: bool = False, tag: Optional[str] = None,
            negative: bool = False, qkey: Optional[tuple] = None) -> bool:
        """Record a freshly resolved value.  ``tag`` is the store name
        the answer depends on (defaults handled by the caller);
        ``negative`` marks NXDOMAIN/NODATA answers for the separate
        accounting (never SERVFAIL — callers must not put those at
        all); ``qkey`` is the ``(qtype, qname)`` question identity, kept
        so tag invalidation can tell the precompiler exactly which
        question shapes it dropped.  Returns True exactly when the entry
        just became *complete* (non-rotatable, or the full variant set
        collected) — the signal the server uses to push the entry to the
        native fast path (see BinderServer._on_query)."""
        if self.size <= 0:
            return False
        e = self._entries.get(key)
        if e is not None and e[0] == epoch:
            if len(e[3]) < self.variants_cap:
                e[3].append(value)
                return not e[4] and len(e[3]) == self.variants_cap
            return False
        if e is not None:
            self._drop(key, e)          # stale epoch: replace cleanly
        if len(self._entries) >= self.size:
            # evict oldest insertion (dicts preserve insertion order)
            old_key = next(iter(self._entries))
            self._drop(old_key, self._entries[old_key])
        if tag is not None:
            tag = self._intern(tag)
        if qkey is not None:
            qkey = (qkey[0], self._intern(qkey[1]))
        self._entries[key] = [epoch, time.monotonic(), 0, [value],
                              not rotatable, tag, False, negative, qkey]
        self._by_tag.setdefault(tag, set()).add(key)
        return not rotatable

    def take_push(self, key, epoch: int):
        """Claim a complete entry for promotion to the native fast
        path: returns ``(variant_values, tag)`` exactly once (marking
        the entry pushed), else None.  Promotion happens on an entry's
        FIRST HIT, not at resolve time — one-shot names (the cache-cold
        workload) then never pay the native push cost, while any name
        asked twice is native from its third query on."""
        e = self._entries.get(key)
        if e is None or e[0] != epoch or e[6] or not (
                e[4] or len(e[3]) >= self.variants_cap):
            return None
        e[6] = True
        return e[3], e[5]

    # -- the compiled-answer table (mutation-time precompiler) --

    def put_compiled(self, qtype: int, qname: str, epoch: int,
                     variants: List[object], rotatable: bool,
                     tag: Optional[str], negative: bool = False,
                     evidence_at: Optional[float] = None) -> None:
        """Install (or replace) the precompiled answer set for one
        question.  ``variants`` is the full rotation set, rendered at
        mutation time — the entry is born complete, so the very next
        query for the name serves from it.

        ``evidence_at`` is the monotonic instant of the most recent
        QUERY evidence for this shape (propagated verbatim through
        drop→re-render cycles; refreshed only by an actual serve) —
        None for speculative installs (the startup seed).  Invalidation
        reports the shape for re-render only while that evidence is
        younger than the expiry window, so a name queried once on a
        hot-churning record stops being re-rendered one window later
        instead of forever."""
        if self.compiled_size <= 0 or not variants:
            return
        qname = self._intern(qname)
        if tag is not None:
            tag = self._intern(tag)
        ckey = (qtype, qname)
        old = self._compiled.get(ckey)
        if old is not None:
            self._drop_tag(old[4], (_COMPILED,) + ckey)
        elif len(self._compiled) >= self.compiled_size:
            old_key = next(iter(self._compiled))
            self._drop_compiled(old_key, self._compiled[old_key])
        self._compiled[ckey] = [epoch, 0, variants, rotatable, tag,
                                negative, evidence_at]
        self._by_tag.setdefault(tag, set()).add((_COMPILED,) + ckey)
        self.compiled_installs += 1

    def get_compiled(self, qtype: int, qname: str, epoch: int):
        """Probe the compiled table: ``(variant, rotatable, tag,
        negative)`` with the rotation cursor advanced, or None.  No time
        expiry — coherence comes from the tag index and the epoch."""
        e = self._compiled.get((qtype, qname))
        if e is None:
            return None
        if e[0] != epoch:
            self._drop_compiled((qtype, qname), e)
            return None
        variants = e[2]
        idx = e[1]
        e[1] = (idx + 1) % len(variants)
        e[6] = time.monotonic()   # fresh serving evidence
        self.compiled_serves += 1
        if e[5]:
            self.neg_hits += 1
        return variants[idx], e[3], e[4], e[5]

    def _drop_compiled(self, ckey, e) -> None:
        del self._compiled[ckey]
        self._drop_tag(e[4], (_COMPILED,) + ckey)

    def invalidate_tag(self, tag: str,
                       dropped: Optional[list] = None) -> int:
        """Drop every entry — per-key and compiled — whose answer
        derives from ``tag``; returns how many were dropped.  When
        ``dropped`` is given, ``(qtype, qname, evidence_at)`` triples
        for the dropped entries with QUERY EVIDENCE inside the expiry
        window are appended to it — the precompiler's re-render work
        list.  A per-key entry's evidence is its creation time (a query
        made it); a compiled entry carries its propagated evidence
        timestamp.  Shapes without recent evidence die silently — churn
        on names nobody queries must cost nothing."""
        keys = self._by_tag.pop(tag, None)
        if not keys:
            return 0
        n = 0
        now = time.monotonic() if dropped is not None else 0.0
        for key in keys:
            if (type(key) is tuple and len(key) == 3
                    and key[0] is _COMPILED):
                ckey = key[1:]
                e = self._compiled.pop(ckey, None)
                if e is not None:
                    n += 1
                    if (dropped is not None and e[6] is not None
                            and now - e[6] <= self.expiry_s):
                        dropped.append(ckey + (e[6],))
            else:
                e = self._entries.pop(key, None)
                if e is not None:
                    n += 1
                    if dropped is not None and e[8] is not None:
                        dropped.append(e[8] + (e[1],))
        self.invalidations += n
        return n

    def remaining_ttl_ms(self, key, epoch: int) -> Optional[float]:
        """Milliseconds until this entry's time expiry — a late-completed
        rotatable entry must carry its *remaining* lifetime into the
        native fast path, not a fresh full window."""
        e = self._entries.get(key)
        if e is None or e[0] != epoch:
            return None
        return max(0.0, (self.expiry_s - (time.monotonic() - e[1]))
                   * 1000.0)

    def stats(self) -> dict:
        """Occupancy + economics for the introspection snapshot
        (binder_tpu/introspect/status.py `answer_cache` section)."""
        hits, misses = self.hits, self.misses
        total = hits + misses
        return {
            "size": self.size,
            "entries": len(self._entries),
            "hits": hits,
            "misses": misses,
            "hit_ratio": (hits / total) if total else 0.0,
            "invalidations": self.invalidations,
            "expiry_ms": self.expiry_s * 1000.0,
            "neg_hits": self.neg_hits,
            "compiled_entries": len(self._compiled),
            "compiled_serves": self.compiled_serves,
            "compiled_installs": self.compiled_installs,
        }

    def clear(self) -> None:
        self._entries.clear()
        self._compiled.clear()
        self._by_tag.clear()
