"""Option handling: compiled defaults ← JSON config file ← CLI flags.

Port of the reference's three-layer merge (``main.js:34-38,51-108``) with
the same flags:

    -a <ms>    cache expiry (legacy, kept for flag compatibility)
    -b <path>  balancer UNIX socket path
    -s <n>     cache size (legacy)
    -p <port>  DNS listen port
    -f <file>  JSON config file (default ./etc/config.json)
    -v         increase verbosity (stackable, -vv -> trace)
    -h         usage

plus the shard-mode long options (docs/operations.md "Sharded
serving"):

    --shards <n|auto>   fork n serving workers behind one
                        SO_REUSEPORT port, supervised by this process
                        — the headline scale-out topology
                        (docs/operations.md).  ``auto`` sizes the
                        group to the machine (one worker per core).
                        (config key ``shards``; 0/absent = classic
                        single-process serving)
    --shard-worker <i>  INTERNAL: run as shard worker i, reading the
                        mutation log from the inherited
                        BINDER_SHARD_FD socketpair

The config file is the SAPI-rendered equivalent (reference
``sapi_manifests/binder/template``): ``dnsDomain``, ``datacenterName``,
optional ``recursion`` block, optional ``store`` block selecting the
coordination-store backend (``zookeeper`` with host/port, or ``fake`` with
an optional fixture file — the testing backend the reference lacks).
"""
from __future__ import annotations

import getopt
import json
import sys
from typing import Dict, List, Optional

DEFAULTS: Dict[str, object] = {
    "expiry": 60000,
    "size": 10000,
    "port": 53,
    "host": "0.0.0.0",
}

USAGE = ("usage: binder [-v] [-a cacheExpiry] [-s cacheSize] [-p port] "
         "[-b balancerSocket] [-f file] [--shards n|auto]")


class ConfigError(Exception):
    pass


def parse_options(argv: Optional[List[str]] = None) -> Dict[str, object]:
    argv = sys.argv[1:] if argv is None else argv
    try:
        optlist, _ = getopt.getopt(argv, "hva:b:s:p:f:",
                                   ["shards=", "shard-worker="])
    except getopt.GetoptError as e:
        raise ConfigError(f"{e}\n{USAGE}")

    cli: Dict[str, object] = {}
    verbosity = 0
    for flag, arg in optlist:
        if flag == "-a":
            cli["expiry"] = int(arg)
        elif flag == "-b":
            cli["balancerSocket"] = arg
        elif flag == "-f":
            cli["configFile"] = arg
        elif flag == "-p":
            cli["port"] = int(arg)
        elif flag == "-s":
            cli["size"] = int(arg)
        elif flag == "--shards":
            # "auto" = size the reuseport group to the machine; main.py
            # resolves it so the config-file form works identically
            cli["shards"] = arg if arg == "auto" else int(arg)
        elif flag == "--shard-worker":
            # internal: spawned by the shard supervisor, never by hand
            cli["shardWorker"] = int(arg)
        elif flag == "-v":
            verbosity += 1
        elif flag == "-h":
            raise ConfigError(USAGE)

    config_file = cli.get("configFile", "./etc/config.json")
    try:
        with open(config_file) as f:
            fopts = json.load(f)
    except (OSError, ValueError) as e:
        raise ConfigError(f"cannot load config {config_file}: {e}")

    options = dict(DEFAULTS)
    options.update(fopts)
    options.update(cli)
    if verbosity:
        options["logLevel"] = "debug" if verbosity == 1 else "trace"
    return options
