"""Config-template renderer — the config-agent/SAPI analog.

The reference's ``etc/config.json`` is not hand-written: config-agent
renders it from SAPI metadata through a mustache template with
Triton-vs-Manta branching (``sapi_manifests/binder/manifest.json:1-4``,
``sapi_manifests/binder/template:1-37`` — the presence of a
``dns_domain`` key selects the Triton branch, which alone carries the
``recursion``/UFDS block).  This module provides the same capability for
the rebuild's deployment glue: a from-scratch renderer for the mustache
subset those templates actually use, plus the manifest convention
(template + output path) driven by ``bin/binder-config-render``.

Supported mustache constructs (exactly what the reference templates
need — this is not a general mustache engine):

- ``{{key}}``           — HTML-escaped interpolation
- ``{{{key}}}``         — raw interpolation
- ``{{#key}}…{{/key}}`` — section: rendered when `key` is truthy; for a
                          list value, rendered once per element with the
                          element pushed onto the context stack
- ``{{^key}}…{{/key}}`` — inverted section: rendered when `key` is
                          falsy/absent
- ``{{! comment}}``     — dropped (may span lines)
- dotted names (``auto.ZONENAME``) resolve through nested dicts

Missing keys render as empty strings, like mustache.
"""
from __future__ import annotations

import html
import json
import re
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["render", "render_manifest", "TemplateError"]

_TAG = re.compile(r"\{\{\{\s*([^}]+?)\s*\}\}\}|\{\{\s*([!#^/]?)\s*([^}]*?)\s*\}\}",
                  re.S)


class TemplateError(Exception):
    """Malformed template (unbalanced or mismatched sections)."""


def _lookup(stack: List[Any], dotted: str) -> Any:
    """Resolve a (possibly dotted) name against the context stack,
    innermost first — standard mustache scoping."""
    head = dotted.split(".", 1)[0]
    for frame in reversed(stack):
        if isinstance(frame, dict) and head in frame:
            value: Any = frame
            for part in dotted.split("."):
                if isinstance(value, dict) and part in value:
                    value = value[part]
                else:
                    return None
            return value
    return None


def _parse(template: str) -> List[Tuple]:
    """Tokenize into a nested tree: ('text', s) | ('var', name, raw) |
    ('section', name, inverted, children)."""
    root: List[Tuple] = []
    stack: List[Tuple[str, List[Tuple]]] = [("", root)]
    pos = 0
    for m in _TAG.finditer(template):
        if m.start() > pos:
            stack[-1][1].append(("text", template[pos:m.start()]))
        pos = m.end()
        if m.group(1) is not None:              # {{{raw}}}
            stack[-1][1].append(("var", m.group(1), True))
            continue
        sigil, name = m.group(2), m.group(3).strip()
        if sigil == "!":
            continue                            # comment
        if sigil in ("#", "^"):
            children: List[Tuple] = []
            stack[-1][1].append(("section", name, sigil == "^", children))
            stack.append((name, children))
        elif sigil == "/":
            if len(stack) == 1 or stack[-1][0] != name:
                raise TemplateError(f"unexpected {{{{/{name}}}}}")
            stack.pop()
        else:
            stack[-1][1].append(("var", name, False))
    if len(stack) != 1:
        raise TemplateError(f"unclosed section {{{{#{stack[-1][0]}}}}}")
    if pos < len(template):
        stack[-1][1].append(("text", template[pos:]))
    return root


def _render_nodes(nodes: List[Tuple], stack: List[Any], out: List[str]) -> None:
    for node in nodes:
        kind = node[0]
        if kind == "text":
            out.append(node[1])
        elif kind == "var":
            value = _lookup(stack, node[1])
            if value is None:
                continue
            s = value if isinstance(value, str) else json.dumps(value) \
                if isinstance(value, (dict, list)) else str(value)
            out.append(s if node[2] else html.escape(s, quote=False))
        else:  # section
            _, name, inverted, children = node
            value = _lookup(stack, name)
            # mustache truthiness: absent / false / "" / empty list are
            # falsy, but an empty hash still renders its section
            truthy = not (value is None or value is False
                          or value == "" or value == [])
            if inverted:
                if not truthy:
                    _render_nodes(children, stack, out)
            elif truthy:
                frames = value if isinstance(value, list) else [value]
                for frame in frames:
                    stack.append(frame)
                    _render_nodes(children, stack, out)
                    stack.pop()


def render(template: str, metadata: Dict[str, Any]) -> str:
    out: List[str] = []
    _render_nodes(_parse(template), [metadata], out)
    return "".join(out)


def render_manifest(manifest_path: str, metadata: Dict[str, Any],
                    template_path: Optional[str] = None,
                    output_path: Optional[str] = None) -> str:
    """Render per the manifest convention: a JSON file with ``name`` and
    ``path`` (the output location) sitting next to a ``template`` file
    (reference ``sapi_manifests/binder/manifest.json``).  Returns the
    rendered text; writes it to `output_path` (or the manifest's
    ``path``) unless that is None and the manifest has no path."""
    import os
    with open(manifest_path) as f:
        manifest = json.load(f)
    tpath = template_path or os.path.join(
        os.path.dirname(manifest_path), "template")
    with open(tpath) as f:
        template = f.read()
    rendered = render(template, metadata)
    dest = output_path or manifest.get("path")
    if dest:
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        with open(dest, "w") as f:
            f.write(rendered)
    return rendered
