"""TCP stream lane: accept fast path + pipelined coalesced writes.

The stream lane used to run on ``asyncio.start_server``: one protocol,
one StreamReader/StreamWriter pair, and one long-lived task per
connection, with two awaits per query.  For persistent pipelined
clients that overhead amortizes; for the one-shot clients that dominate
real TCP traffic (RFC 1035 §4.2.2 truncation retries, non-keep-alive
stub resolvers) it WAS the serve path — the r05 bench put a fresh
connection at ~137µs (tcp1) and the tc=1 UDP→TCP retry flow at 10.8ms
p50, against a 3µs pipelined serve.

This module replaces that machinery with plain readiness callbacks on
the shared event loop:

- **Accept fast path** — the listener arms ``TCP_DEFER_ACCEPT``, so
  accept-readiness normally fires with the client's first frame already
  in the socket buffer.  The accept callback reads it, serves every
  complete frame through the same native-bulk/raw-lane/generic ladder
  the old protocol used, and answers with one vectored write — accept,
  read, serve, and respond in a single loop iteration, no task, no
  streams.  A one-shot client's close lands as EOF on a later readiness
  callback and tears the state down; only clients that keep sending get
  *promoted* (an accounting state — the serve machinery is already the
  pipelined one).
- **Pipelined write coalescing** — responses produced while draining a
  read chunk, and async completions (the recursion path) landing in the
  same loop tick, are sent as ONE vectored write (``sendmsg``).
  Responses go out as they complete, out of order per RFC 7766 §6.2.1.1
  — a miss never head-of-line-blocks a batch of hits.
- **Hardened connection table** — the write-buffer cap disconnects slow
  readers with an RST (``abort``) so the kernel send buffer is freed
  immediately; half-closed clients (send-then-SHUT_WR is a legitimate
  shape) are held only until their owed responses are written, under a
  bounded grace; mid-frame RSTs shed the connection without touching
  the rest of the table.  Idle enforcement is a single periodic sweep
  owned by :class:`~binder_tpu.dns.server.DnsServer` — one timer for
  the whole table, not one per connection.

Every transition feeds :class:`TcpStats`, folded into the
``binder_tcp_*`` Prometheus family at scrape time and surfaced in the
``/status`` ``tcp`` section (docs/observability.md).
"""
from __future__ import annotations

import socket
import struct

#: scatter-gather ceiling per sendmsg (POSIX IOV_MAX is 1024 on Linux);
#: a flush carrying more frames sends the first window and lets the
#: short-write tail logic queue the rest
_IOV_MAX = 1024


class TcpStats:
    """Plain-int counters for the stream lane.  The serve path pays an
    attribute increment; the labelled-metric work happens once per
    scrape when ``BinderServer._fold_engine_counters`` folds the deltas
    into the Prometheus collectors."""

    FIELDS = ("accepts", "fast_serves", "promotions", "oneshot_closes",
              "idle_timeouts", "slow_reader_drops", "coalesced_writes",
              "coalesced_frames", "half_closes", "rst_drops")
    __slots__ = FIELDS

    def __init__(self) -> None:
        for field in self.FIELDS:
            setattr(self, field, 0)

    def snapshot(self) -> dict:
        return {field: getattr(self, field) for field in self.FIELDS}


class TcpConn:
    """One client connection on the stream lane.

    Owned entirely by readiness callbacks; holds no task and no
    coroutine.  The read side reframes RFC 1035 §4.2.2 length-prefixed
    queries and dispatches them through the server's ``_handle_raw``;
    the write side batches frames and enforces the slow-reader cap.
    """

    __slots__ = ("srv", "sock", "fd", "loop", "peer", "src", "buf",
                 "out", "out_nframes", "wbuf", "flush_scheduled",
                 "reader_on", "writer_on", "deadline", "promoted",
                 "served", "q_out", "eof", "closed", "grace", "in_feed",
                 "nodelay")

    def __init__(self, srv, sock, peer, loop) -> None:
        self.srv = srv
        self.sock = sock
        self.fd = sock.fileno()
        self.loop = loop
        self.peer = peer
        self.src = (peer[0], peer[1])
        self.buf = b""
        self.out: list = []          # buffers awaiting the next flush
        self.out_nframes = 0         # response FRAMES those carry (a
        #                              native bulk block is one buffer,
        #                              many frames)
        self.wbuf = None             # bytearray once a write went short
        self.flush_scheduled = False
        self.reader_on = False
        self.writer_on = False
        idle = srv.tcp_idle_timeout
        self.deadline = (loop.time() + idle) if idle else None
        self.promoted = False
        self.served = 0              # complete frames dispatched
        self.q_out = 0               # dispatched frames not yet answered
        self.eof = False
        self.closed = False
        self.grace = None            # half-close drain deadline handle
        self.in_feed = False
        self.nodelay = False

    def start(self) -> None:
        srv = self.srv
        srv._conns.add(self)
        srv._tcp_conns.add(self)
        # DEFER_ACCEPT means accept-readiness normally arrives with the
        # first frame already buffered: serve it NOW, inside the accept
        # callback — a one-shot client's whole visit is one loop
        # iteration (accept → read → serve → vectored write)
        self._on_readable()
        if not self.closed and not self.eof and not self.reader_on:
            self.loop.add_reader(self.fd, self._on_readable)
            self.reader_on = True

    # -- read side --

    def _on_readable(self) -> None:
        if self.closed:
            return
        try:
            chunk = self.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            # RST, possibly mid-frame: shed this connection; the rest
            # of the table (and any partial frame state) dies with it
            self.srv.tcp_stats.rst_drops += 1
            self.close()
            return
        if not chunk:
            self._on_eof()
            return
        if self.served and not self.promoted:
            # kept sending after the served first burst: a real
            # pipelining client — account the promotion (the serve
            # machinery is already the pipelined one)
            self.promoted = True
            self.srv.tcp_stats.promotions += 1
            self._arm_nodelay()
        self._feed(chunk)

    def _arm_nodelay(self) -> None:
        """TCP_NODELAY, the moment a SECOND response write becomes
        possible: repeated small framed writes with unacked data are
        exactly the shape Nagle + delayed ACK turn into 40ms stalls.
        A one-shot connection's single write never needs it (Nagle
        sends the first segment immediately), so the accept fast path
        skips the syscall."""
        if self.nodelay:
            return
        self.nodelay = True
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
        except OSError:
            pass

    def _feed(self, chunk: bytes) -> None:
        srv = self.srv
        buf = self.buf + chunk if self.buf else chunk
        off = 0
        dispatched = 0
        self.in_feed = True
        try:
            # native bulk serve first: every complete frame the C
            # cache/zone can answer is served and framed in ONE call;
            # only misses (and frames past the C arena cap) fall
            # through to the per-frame path
            if len(buf) >= 2:
                bulk = srv._serve_frames_bulk(buf, self.src)
                if bulk is not None:
                    resp, consumed, fmisses = bulk
                    # frames in the consumed region (cheap header walk;
                    # the C side already validated the lengths)
                    nblock = 0
                    o = 0
                    while o + 2 <= consumed:
                        o += 2 + ((buf[o] << 8) | buf[o + 1])
                        nblock += 1
                    dispatched += nblock
                    if resp:
                        self.out.append(resp)
                        self.out_nframes += nblock - len(fmisses)
                    for payload in fmisses:
                        self.q_out += 1
                        try:
                            # already declined by the bulk serve: skip
                            # the redundant per-payload fastpath probe
                            srv._handle_raw(payload, self.src, "tcp",
                                            self._send_wire,
                                            fastpath_checked=True)
                        except Exception:
                            srv.log.exception(
                                "unhandled error processing TCP frame "
                                "from %s", self.peer[0])
                    off = consumed
                    if resp and srv.fastpath_log_flush is not None:
                        try:
                            srv.fastpath_log_flush()
                        except Exception:
                            srv.log.exception(
                                "query-log ring drain failed")
            n = len(buf)
            while n - off >= 2:
                length = (buf[off] << 8) | buf[off + 1]
                if length == 0:
                    # a zero-length frame is never valid DNS (min
                    # header is 12 bytes) and would count as free
                    # deadline progress for a slot-squatting client:
                    # drop the connection outright
                    srv.log.debug(
                        "closing TCP connection from %s: zero-length "
                        "frame", self.peer[0])
                    self.in_feed = False
                    self._flush()
                    self.close()
                    return
                if n - off - 2 < length:
                    break
                self.q_out += 1
                dispatched += 1
                try:
                    srv._handle_raw(buf[off + 2:off + 2 + length],
                                    self.src, "tcp", self._send_wire)
                except Exception:
                    # isolate per frame: a bug on one query must not
                    # abandon the rest of the batch
                    srv.log.exception(
                        "unhandled error processing TCP frame from %s",
                        self.peer[0])
                off += 2 + length
            self.buf = buf[off:] if off else buf
            if dispatched:
                idle = srv.tcp_idle_timeout
                if idle:
                    # only COMPLETE frames advance the idle deadline: a
                    # client trickling bytes gets the same whole-frame
                    # deadline as a silent one
                    self.deadline = self.loop.time() + idle
                self.served += dispatched
                if not self.promoted:
                    srv.tcp_stats.fast_serves += dispatched
        finally:
            self.in_feed = False
        self._flush()

    def _on_eof(self) -> None:
        srv = self.srv
        self.eof = True
        # no more data will arrive; a level-triggered reader would spin
        if self.reader_on:
            try:
                self.loop.remove_reader(self.fd)
            except (OSError, ValueError):
                pass
            self.reader_on = False
        if self.q_out == 0 and not self.out and self.wbuf is None:
            self._maybe_finish()
            return
        # half-close with responses still owed (send-then-SHUT_WR is a
        # legitimate RFC 7766 client shape): serve them out under a
        # bounded grace, so a query that never answers (malformed drop)
        # cannot wedge the slot
        srv.tcp_stats.half_closes += 1
        grace = min(srv.tcp_idle_timeout or 5.0, 5.0)
        self.grace = self.loop.call_later(grace, self.close)

    # -- write side --

    def _send_wire(self, wire: bytes) -> None:
        # one response per dispatched query at most (QueryCtx.responded
        # guards); q_out tracks responses still owed to a half-closed
        # connection
        if self.q_out:
            self.q_out -= 1
        self.send_framed(struct.pack(">H", len(wire)) + wire)

    def send_framed(self, framed: bytes) -> None:
        if self.closed:
            return   # late (async) response to a dead connection: drop
        self.out.append(framed)
        self.out_nframes += 1
        if not self.in_feed and not self.flush_scheduled:
            # async completions (the recursion path): coalesce every
            # response landing in this loop tick into one vectored
            # write — upstream answers arrive in batches, so their
            # completions cluster in one pass
            self.flush_scheduled = True
            self.loop.call_soon(self._flush_cb)

    def _flush_cb(self) -> None:
        self.flush_scheduled = False
        self._flush()

    def _count_coalesced(self) -> None:
        """Account one flush batch: a batch carrying more than one
        response frame (vectored write, or a native bulk block) is a
        coalesced write."""
        n = self.out_nframes
        self.out_nframes = 0
        if n > 1:
            stats = self.srv.tcp_stats
            stats.coalesced_writes += 1
            stats.coalesced_frames += n

    def _flush(self) -> None:
        if self.closed:
            return
        out = self.out
        if self.wbuf is not None:
            # a previous write went short; the writability callback
            # owns the socket until the backlog drains
            if out:
                self._count_coalesced()
                wbuf = self.wbuf
                for framed in out:
                    wbuf += framed
                out.clear()
                self._enforce_write_cap()
            return
        if not out:
            self._maybe_finish()
            return
        self._count_coalesced()
        nframes = len(out)
        total = 0
        for framed in out:
            total += len(framed)
        try:
            if nframes == 1:
                sent = self.sock.send(out[0])
            else:
                # past IOV_MAX the kernel rejects the vector outright
                # (EMSGSIZE); the unsent frames fall into the
                # short-write tail below
                sent = self.sock.sendmsg(out[:_IOV_MAX])
        except (BlockingIOError, InterruptedError):
            sent = 0
        except OSError:
            out.clear()
            self.close()
            return
        if sent == total:
            out.clear()
            if self.q_out and not self.nodelay:
                # responses still owed (async handlers in flight): a
                # further write is coming while this one may be unacked
                self._arm_nodelay()
            self._maybe_finish()
            return
        # short write: keep the tail, let writability drain it
        tail = bytearray()
        for framed in out:
            if sent >= len(framed):
                sent -= len(framed)
                continue
            tail += framed[sent:] if sent else framed
            sent = 0
        out.clear()
        self.wbuf = tail
        if not self.writer_on:
            self.loop.add_writer(self.fd, self._on_writable)
            self.writer_on = True
        self._enforce_write_cap()

    def _on_writable(self) -> None:
        if self.closed:
            return
        wbuf = self.wbuf
        try:
            sent = self.sock.send(wbuf)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self.close()
            return
        del wbuf[:sent]
        if not wbuf:
            self.wbuf = None
            if self.writer_on:
                try:
                    self.loop.remove_writer(self.fd)
                except (OSError, ValueError):
                    pass
                self.writer_on = False
            if self.out:
                self._flush()
            else:
                self._maybe_finish()

    def _enforce_write_cap(self) -> None:
        """A slow reader is disconnected the moment its unsent backlog
        exceeds ``max_tcp_write_buffer`` — never buffered unboundedly.
        The disconnect is an RST so the kernel's own send buffer (which
        the peer also isn't draining) is freed immediately."""
        srv = self.srv
        if self.wbuf is None or len(self.wbuf) <= srv.max_tcp_write_buffer:
            return
        srv.tcp_stats.slow_reader_drops += 1
        srv.log.warning(
            "TCP client %s not reading responses (>%d bytes queued), "
            "aborting", self.peer[0], srv.max_tcp_write_buffer)
        if srv.recorder is not None:
            srv.recorder.record(
                "tcp-slow-reader", client=self.peer[0],
                queued=len(self.wbuf), cap=srv.max_tcp_write_buffer)
        self.abort()

    # -- teardown --

    def _maybe_finish(self) -> None:
        """Close a half-closed connection once every owed response is
        written; account the one-shot close for never-promoted
        connections (the accept-fast-path's whole population)."""
        if not (self.eof and self.q_out == 0 and not self.out
                and self.wbuf is None):
            return
        if self.served and not self.promoted:
            self.srv.tcp_stats.oneshot_closes += 1
        self.close()

    def abort(self) -> None:
        """RST the connection: SO_LINGER(0) + close drops the queued
        kernel send buffer instead of draining it toward a peer that
        has stopped reading."""
        if not self.closed:
            try:
                self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                     struct.pack("ii", 1, 0))
            except OSError:
                pass
        self.close()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        if self.grace is not None:
            self.grace.cancel()
            self.grace = None
        if self.reader_on:
            try:
                self.loop.remove_reader(self.fd)
            except (OSError, ValueError):
                pass
            self.reader_on = False
        if self.writer_on:
            try:
                self.loop.remove_writer(self.fd)
            except (OSError, ValueError):
                pass
            self.writer_on = False
        self.srv._conns.discard(self)
        self.srv._tcp_conns.discard(self)
        self.out.clear()
        self.out_nframes = 0
        self.wbuf = None
        try:
            self.sock.close()
        except OSError:
            pass
