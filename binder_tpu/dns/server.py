"""DNS server transport engine (the mname-equivalent, asyncio).

Owns sockets and framing; knows nothing about resolution.  The binder layer
(``binder_tpu.server``) attaches ``on_query`` / ``on_after`` hooks, exactly
like the reference attaches handlers to mname's ``query``/``after`` events
(``lib/server.js:471,509``).

Listeners (reference ``lib/server.js:609-653``):
- ``listen_udp``   — datagram per query, truncation per EDNS payload.
- ``listen_tcp``   — RFC 1035 §4.2.2 two-byte length framing, many queries
  per connection.
- ``listen_balancer`` — UNIX-socket backend side of the balancer protocol
  (docs/balancer-protocol.md) carrying original client addresses.

Error tolerance: EHOSTUNREACH (asymmetric routing) is logged and swallowed
(reference ``lib/server.js:593-607``); malformed packets get FORMERR when a
query id is recoverable, else are dropped.
"""
from __future__ import annotations

import asyncio
import errno
import ipaddress
import logging
import os
import socket
import struct
import time
from typing import Callable, List, Optional, Tuple

from binder_tpu.dns.query import QueryCtx
from binder_tpu.dns.stream import TcpConn, TcpStats
from binder_tpu.dns.wire import Message, OPTRecord, Rcode, WireError

try:  # batched recvmmsg/sendmmsg datapath (built by `make -C native`)
    from binder_tpu import _binderfastio as _fastio
except ImportError:  # pure-Python fallback: recvfrom/sendto per packet
    _fastio = None

# socket-free serve entry for the TCP / balancer lanes (older builds of
# the extension predate it)
_fp_serve_wire = getattr(_fastio, "fastpath_serve_wire", None)
# bulk TCP-frame serve: every complete frame in a read chunk handled in
# one C call (hits framed back as one writer call; misses surfaced)
_fp_serve_frames = getattr(_fastio, "fastpath_serve_frames", None)
# bulk balancer-frame serve with direct return: every UDP-transport hit
# in a read chunk is answered straight onto the balancer's passed
# client-facing socket via one sendmmsg; misses/control/TCP frames
# surface for the Python lane
_fp_serve_balancer = getattr(_fastio, "fastpath_serve_balancer", None)

# Sentinel an on_query hook may return instead of an awaitable: the
# query is in flight and the HANDLER owns its completion — response AND
# after-hook — via its own future callbacks (the recursion fast path).
# The engine then creates no task for it.
HANDLED_ASYNC = object()

BALANCER_VERSION = 1
BALANCER_HDR = 21  # version + family + transport + 16-byte addr + port
MAX_FRAME = 65_556
TRANSPORT_UDP = 0
TRANSPORT_TCP = 1
# response-only marker: route like UDP but no cache layer may keep it
# (recursion answers belong to another DC's store)
TRANSPORT_UDP_NO_STORE = 2

# Control-frame opcodes (family 0; the transport byte is the opcode).
CTL_GEN = 0          # backend→balancer: generation report
CTL_INVALIDATE = 1   # backend→balancer: dependency-tag invalidate
# Direct-return negotiation, both directions.  Backend→balancer: this
# backend accepts a passed client socket (so the balancer never sends
# the frame first — an old backend would fail the family check below
# and drop the link).  Balancer→backend: rides the sendmsg whose
# SCM_RIGHTS ancillary data carries the client-facing UDP socket.
CTL_DIRECT = 2


def pack_balancer_frame(family: int, addr: str, port: int,
                        payload: bytes,
                        transport: int = TRANSPORT_UDP) -> bytes:
    raw = (ipaddress.IPv4Address(addr).packed + b"\x00" * 12
           if family == 4 else ipaddress.IPv6Address(addr).packed)
    return struct.pack(">IBBB16sH", BALANCER_HDR + len(payload),
                       BALANCER_VERSION, family, transport, raw,
                       port) + payload


def pack_gen_frame(gen: int) -> bytes:
    """Control frame reporting the mirror-cache generation (epoch) to
    the balancer (family 0 marks control; the transport byte is the
    opcode, 0 = generation report; the 16-byte address field carries the
    generation, big-endian, in its first 8 bytes).  An advance tells the
    balancer every cached entry from this backend is stale
    (docs/balancer-protocol.md)."""
    return struct.pack(">IBBB16sH", BALANCER_HDR, BALANCER_VERSION, 0, 0,
                       (gen & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "big"), 0)


def pack_invalidate_frame(tag_wire: bytes) -> bytes:
    """Control frame (opcode 1) invalidating one dependency tag at the
    balancer: the payload after the frame header is the lowercased
    qname-wire form of the store name whose answers a mutation changed
    (docs/balancer-protocol.md)."""
    return struct.pack(">IBBB16sH", BALANCER_HDR + len(tag_wire),
                       BALANCER_VERSION, 0, 1, b"\x00" * 16,
                       0) + tag_wire


def pack_direct_frame() -> bytes:
    """Control frame (opcode 2) announcing direct-return capability to
    the balancer.  An old balancer ignores the unknown opcode; a new one
    answers by passing its client-facing UDP socket over SCM_RIGHTS on a
    frame with the same opcode (docs/balancer-protocol.md)."""
    return struct.pack(">IBBB16sH", BALANCER_HDR, BALANCER_VERSION, 0,
                       CTL_DIRECT, b"\x00" * 16, 0)


def unpack_balancer_frame(frame: bytes) -> Tuple[int, str, int, int, bytes]:
    version, family, transport, raw, port = struct.unpack_from(
        ">BBB16sH", frame, 0)
    if version != BALANCER_VERSION:
        raise WireError(f"unknown balancer protocol version {version}")
    if transport not in (TRANSPORT_UDP, TRANSPORT_TCP,
                         TRANSPORT_UDP_NO_STORE):
        raise WireError(f"bad transport {transport}")
    if family == 4:
        addr = str(ipaddress.IPv4Address(raw[:4]))
    elif family == 6:
        addr = str(ipaddress.IPv6Address(raw))
    else:
        raise WireError(f"bad address family {family}")
    return family, addr, port, transport, frame[BALANCER_HDR:]


class BalancerLink:
    """One balancer connection, backend side, on a raw socket (asyncio
    streams would discard the SCM_RIGHTS ancillary data that carries
    the passed client socket).

    Lifecycle: on accept the backend reports its generation, then
    announces direct-return capability (opcode 2).  A capable balancer
    answers with an fd-pass frame whose ancillary data is its
    client-facing UDP socket; from then on every UDP-transport response
    leaves straight for the client from this process — one sendmmsg per
    read chunk on the native fast path — and only TCP-framed responses
    ride the relay.  An old balancer skips the unknown opcode and the
    link stays a pure relay, byte-compatible with the classic protocol.

    Relay writes are append-ordered into one buffer, which preserves
    the causal order the old per-connection lock defended: a response
    computed under pre-mutation data is appended synchronously when its
    send callback runs, before the call_soon that broadcasts the
    generation frame invalidating it can fire.
    """

    #: recv_fds chunk size — large enough that a deep balancer pipeline
    #: drains in few syscalls
    _READ_CHUNK = 256 * 1024
    #: queued-relay cap: a balancer that stops reading is dead weight,
    #: not backpressure — drop the link and let it reconnect
    _MAX_WRITE_BUFFER = 8 * 1024 * 1024

    def __init__(self, engine: "DnsServer", sock: socket.socket,
                 loop) -> None:
        self.engine = engine
        self.sock = sock
        self.loop = loop
        self.fd = sock.fileno()
        self.log = engine.log
        self._rbuf = bytearray()
        self._wbuf = bytearray()
        self._writing = False      # add_writer armed
        self._flush_soon = False   # coalesced relay flush scheduled
        self._fds: list = []       # passed fds awaiting their frame
        self.direct_sock: Optional[socket.socket] = None
        # non-None while a read pass is draining: synchronous direct
        # responses batch into it and flush as one sendmmsg
        self._direct_box: List[Optional[list]] = [None]
        self._direct_late: list = []
        self._closed = False

    def start(self) -> None:
        engine = self.engine
        engine._conns.add(self)
        if engine.gen_source is not None:
            # report our generation immediately so the balancer can
            # cache from the first response; per-link and unconditional
            # (a fresh balancer knows nothing), also seeds the dedupe
            # tracker
            val = engine.gen_source()
            self.send_frame(pack_gen_frame(val))
            engine._last_gen_sent = val
            engine._balancer_writers[self] = True
        if engine.balancer_direct_return:
            self.send_frame(pack_direct_frame())
        self.loop.add_reader(self.fd, self._on_readable)

    # -- reads --

    def _on_readable(self) -> None:
        try:
            data, fds, _flags, _addr = socket.recv_fds(
                self.sock, self._READ_CHUNK, 8)
        except (BlockingIOError, InterruptedError):
            return
        except OSError as e:
            self.log.error("balancer link read failed: %s", e)
            self.close()
            return
        for fd in fds:
            os.set_inheritable(fd, False)
        self._fds.extend(fds)
        if not data and not fds:
            self.close()   # EOF
            return
        self._rbuf += data
        self._process()

    def _process(self) -> None:
        engine = self.engine
        buf = self._rbuf
        out: list = []
        self._direct_box[0] = out
        try:
            fp = engine.fastpath
            if (fp is not None and _fp_serve_balancer is not None
                    and self.direct_sock is not None
                    and (engine.fastpath_gate is None
                         or engine.fastpath_gate())):
                gen = engine.fastpath_gen() if engine.fastpath_gen else 0
                try:
                    consumed, _served, misses = _fp_serve_balancer(
                        fp, buf, gen, self.direct_sock.fileno())
                except OSError as e:
                    # the passed socket went bad under us: drop direct
                    # mode, the relay lane still works, and the whole
                    # chunk re-parses below (a duplicate UDP reply for
                    # an already-sent hit is harmless — clients dedupe
                    # by query id)
                    self.log.error("direct-return send failed, "
                                   "reverting to relay: %s", e)
                    self._drop_direct()
                else:
                    del buf[:consumed]
                    for frame in misses:
                        if not self._handle_frame(bytes(frame),
                                                  from_native=True):
                            self.close()
                            return
                    log_flush = engine.fastpath_log_flush
                    if log_flush is not None:
                        try:
                            log_flush()
                        except Exception:
                            self.log.exception(
                                "query-log ring drain failed")
            # Python lane: whatever the native pass left behind —
            # everything, when there is no cache / no passed fd / the
            # gate is closed; only a trailing partial or garbage frame
            # otherwise
            while not self._closed:
                if len(buf) < 4:
                    break
                length = int.from_bytes(buf[:4], "big")
                if length < BALANCER_HDR or length > MAX_FRAME:
                    self.log.error("balancer frame length %d out of "
                                   "range", length)
                    self.close()
                    return
                if len(buf) < 4 + length:
                    break
                frame = bytes(buf[4:4 + length])
                del buf[:4 + length]
                if not self._handle_frame(frame):
                    self.close()
                    return
        finally:
            self._direct_box[0] = None
            if out and not self._closed:
                self._send_direct_batch(out)
            self._flush()

    def _handle_frame(self, frame: bytes,
                      from_native: bool = False) -> bool:
        """One complete frame (no length prefix).  Returns False on a
        protocol error that must drop the link."""
        engine = self.engine
        if frame[0] != BALANCER_VERSION:
            engine.log.error("balancer protocol error: unknown balancer "
                             "protocol version %d", frame[0])
            return False
        if frame[1] == 0:
            # control frame from the balancer; unknown opcodes are
            # skipped so the protocol can grow without lockstep
            # upgrades (mirrors the balancer's own consume loop)
            if frame[2] == CTL_DIRECT:
                self._adopt_direct_fd()
            else:
                engine.log.debug("ignoring balancer control opcode %d",
                                 frame[2])
            return True
        try:
            family, addr, port, transport, payload = \
                unpack_balancer_frame(frame)
        except WireError as e:
            engine.log.error("balancer protocol error: %s", e)
            return False
        if transport == TRANSPORT_UDP_NO_STORE:
            # response-only marker; never valid on a request
            engine.log.error("balancer protocol error: "
                             "do-not-store transport on a request")
            return False

        ctx_box: list = []

        def send(wire: bytes, f=family, a=addr, p=port, t=transport,
                 box=ctx_box) -> None:
            if t == TRANSPORT_UDP and self.direct_sock is not None:
                # direct return: the response leaves on the balancer's
                # own client-facing socket and never re-enters the
                # balancer — which also makes the do-not-store marker
                # moot (nothing sees the response to cache it)
                self._send_direct(wire, (a, p))
                return
            t_out = t
            if t == TRANSPORT_UDP and box and box[0].no_store:
                # recursion-produced responses carry the do-not-store
                # marker so the balancer won't cache another DC's data
                # under our generation
                t_out = TRANSPORT_UDP_NO_STORE
            self.send_frame(pack_balancer_frame(f, a, p, wire,
                                                transport=t_out))

        try:
            engine._handle_raw(
                payload, (addr, port), "balancer", send,
                client_transport=("tcp" if transport == TRANSPORT_TCP
                                  else "udp"),
                ctx_box=ctx_box,
                # the native pass already probed the cache for the
                # UDP-transport frames it surfaces; TCP frames bypass
                # it there and still get their serve_wire probe
                fastpath_checked=(from_native
                                  and transport == TRANSPORT_UDP))
        except Exception:
            # isolate per frame: a bug on one query must not drop the
            # link and every other client multiplexed on it
            engine.log.exception("unhandled error processing balancer "
                                 "frame for %s", addr)
        return True

    # -- direct return --

    def _adopt_direct_fd(self) -> None:
        if not self._fds:
            # ancillary data stripped (or a confused balancer): stay on
            # the relay lane, which is always correct
            self.log.warning("balancer fd-pass frame carried no "
                             "descriptor; staying on relay lane")
            return
        fd = self._fds.pop(0)
        self._drop_direct()
        # the passed descriptor shares the balancer's file description:
        # O_NONBLOCK is already set over there and toggling it here
        # would flip it under the balancer too
        self.direct_sock = socket.socket(fileno=fd)
        self.log.info("balancer passed its client socket: UDP "
                      "responses now return directly")

    def _drop_direct(self) -> None:
        if self.direct_sock is not None:
            try:
                self.direct_sock.close()
            except OSError:
                pass
            self.direct_sock = None

    def _send_direct(self, wire: bytes, addr) -> None:
        box = self._direct_box[0]
        if box is not None:
            box.append((wire, addr))
            return
        # late (async-completed) response: coalesce per event-loop pass
        if not self._direct_late:
            self.loop.call_soon(self._flush_direct_late)
        self._direct_late.append((wire, addr))

    def _flush_direct_late(self) -> None:
        out = self._direct_late[:]
        self._direct_late.clear()
        if out and not self._closed:
            self._send_direct_batch(out)

    def _send_direct_batch(self, out: list) -> None:
        sock = self.direct_sock
        if sock is None:
            # direct mode dropped between queueing and flush: the
            # responses are still deliverable over the relay
            for wire, (a, p) in out:
                fam = 6 if ":" in a else 4
                self.send_frame(pack_balancer_frame(fam, a, p, wire))
            return
        if _fastio is not None:
            try:
                sent = _fastio.send_batch(sock.fileno(), out)
                if sent < len(out):
                    # socket buffer full: one retry, then drop (UDP
                    # clients retransmit; blocking would stall every
                    # other client on the loop)
                    sent += _fastio.send_batch(sock.fileno(), out[sent:])
                    if sent < len(out):
                        self.log.debug("dropped %d direct responses "
                                       "(send buffer full)",
                                       len(out) - sent)
            except OSError as e:
                self.log.error("direct-return send failed, reverting "
                               "to relay: %s", e)
                self._drop_direct()
            return
        # pure-Python fallback (extension not built)
        for wire, addr in out:
            try:
                sock.sendto(wire, addr)
            except (BlockingIOError, InterruptedError):
                pass
            except OSError as e:
                self.log.error("direct-return send failed, reverting "
                               "to relay: %s", e)
                self._drop_direct()
                return

    # -- relay / control-frame writes --

    def send_frame(self, data: bytes) -> None:
        if self._closed:
            return
        self._wbuf += data
        if len(self._wbuf) > self._MAX_WRITE_BUFFER:
            self.log.error("balancer link write buffer overflow "
                           "(%d bytes): dropping link", len(self._wbuf))
            self.close()
            return
        if not self._writing and not self._flush_soon:
            # coalesce same-turn frames into one send
            self._flush_soon = True
            self.loop.call_soon(self._flush_scheduled)

    def _flush_scheduled(self) -> None:
        self._flush_soon = False
        self._flush()

    def _flush(self) -> None:
        if self._closed or not self._wbuf:
            return
        try:
            n = self.sock.send(self._wbuf)
        except (BlockingIOError, InterruptedError):
            n = 0
        except OSError:
            self.close()   # balancer went away; responses are lost
            return
        if n:
            del self._wbuf[:n]
        if self._wbuf and not self._writing:
            self._writing = True
            self.loop.add_writer(self.fd, self._flush)
        elif not self._wbuf and self._writing:
            self._writing = False
            self.loop.remove_writer(self.fd)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        engine = self.engine
        engine._balancer_writers.pop(self, None)
        engine._conns.discard(self)
        try:
            self.loop.remove_reader(self.fd)
        except (OSError, ValueError):
            pass
        if self._writing:
            self._writing = False
            try:
                self.loop.remove_writer(self.fd)
            except (OSError, ValueError):
                pass
        for fd in self._fds:
            try:
                os.close(fd)
            except OSError:
                pass
        self._fds.clear()
        self._drop_direct()
        try:
            self.sock.close()
        except OSError:
            pass


class DnsServer:
    #: Bounds for the TCP front (the reference's mname engine had none;
    #: a DNS front end that one slow peer can fd-starve is not done).
    #: Both are per-server and overridable at construction.
    TCP_IDLE_TIMEOUT = 30.0    # seconds without a complete read
    MAX_TCP_CONNS = 1024
    MAX_TCP_WRITE_BUFFER = 256 * 1024   # bytes queued to one client

    def __init__(self, log: Optional[logging.Logger] = None,
                 name: str = "binder",
                 tcp_idle_timeout: Optional[float] = None,
                 max_tcp_conns: Optional[int] = None,
                 max_tcp_write_buffer: Optional[int] = None) -> None:
        self.log = log or logging.getLogger("binder.dns")
        self.name = name
        self.tcp_idle_timeout = (self.TCP_IDLE_TIMEOUT
                                 if tcp_idle_timeout is None
                                 else tcp_idle_timeout)
        self.max_tcp_conns = (self.MAX_TCP_CONNS if max_tcp_conns is None
                              else max_tcp_conns)
        self.max_tcp_write_buffer = (self.MAX_TCP_WRITE_BUFFER
                                     if max_tcp_write_buffer is None
                                     else max_tcp_write_buffer)
        # TCP clients only (balancer links are trusted local peers and
        # excluded from the cap/idle policy); members are TcpConn
        # objects (dns/stream.py)
        self._tcp_conns: set = set()
        # stream-lane counters (accepts, fast serves, promotions,
        # coalesce economics, drop reasons) — folded into binder_tcp_*
        # at scrape time by BinderServer
        self.tcp_stats = TcpStats()
        # cap-refusal accounting: a connect flood at the cap must not
        # become a log flood, so refusals log at most once per interval
        # (with the count of everything refused since the last line)
        self.tcp_cap_refusals = 0
        self._cap_log_last = 0.0
        self._cap_log_pending = 0
        # late (async-completed) UDP responses dropped at a full socket
        # buffer: counted + flight-recorded so drops are VISIBLE at
        # scale instead of a debug line nobody has enabled
        # (binder_udp_late_drops_total; counter child installed by
        # BinderServer, flight events rate-limited to one per window)
        self.udp_late_drops = 0
        self.late_drop_counter = None   # metrics child or None
        self._late_drop_event_last = 0.0
        self.LATE_DROP_EVENT_WINDOW_S = 1.0
        self.on_query: Optional[Callable] = None   # async (QueryCtx) -> None
        self.on_after: Optional[Callable] = None   # sync  (QueryCtx) -> None
        self._udp_socks: List[tuple] = []   # (loop, socket)
        self._tcp_listeners: List[tuple] = []   # (loop, socket)
        self._tcp_sweep_handle = None       # idle-sweep TimerHandle
        self._unix_servers: List[tuple] = []   # (loop, socket, path)
        self._tasks: set = set()
        # live stream connections (TCP clients, balancer links) — must be
        # force-closed on shutdown or Server.wait_closed() blocks on
        # handlers stuck in read
        self._conns: set = set()
        self._decode_cache: dict = {}
        # Raw resolve lane (installed by BinderServer): handles the
        # dominant query shape (single A/IN question) by direct wire
        # assembly, skipping Message decode/encode.  Returns True when it
        # fully handled the packet (response sent, metrics recorded);
        # anything it can't prove simple falls through to the generic
        # path below.
        self.raw_lane: Optional[Callable] = None
        # Native fast-path cache (installed by BinderServer when the
        # _binderfastio extension is built): answer-cache hits are served
        # inside the C drain loop and never surface here.  `fastpath_gen`
        # supplies the current mirror-cache generation per batch;
        # `fastpath_gate` disables the path when every query must reach
        # Python (per-query logging or probes active).
        self.fastpath = None
        self.fastpath_gen: Optional[Callable[[], int]] = None
        self.fastpath_gate: Optional[Callable[[], bool]] = None
        # Drains the native query-log ring (installed by BinderServer in
        # the logged posture); called once per UDP drain pass so ring
        # writes amortize over a whole batch of serves.
        self.fastpath_log_flush: Optional[Callable[[], None]] = None
        # Balancer answer-cache support: control frames let the balancer
        # cache responses with backend-driven invalidation.
        # `gen_source` supplies the current generation/epoch;
        # notify_mutation (wired to MirrorCache.on_mutation) broadcasts
        # it, coalesced to one frame per event-loop turn.
        # notify_invalidate (wired to MirrorCache.on_invalidate)
        # broadcasts per-name invalidate frames (opcode 1), coalesced
        # the same way, so ordinary store churn drops only the affected
        # balancer entries.
        self.gen_source: Optional[Callable[[], int]] = None
        # In-flight query table (introspection): queries whose handler
        # went async — the only ones observable "in flight" from outside
        # (sync completions never leave the dispatch call).  Keyed by
        # id(query); values are the live QueryCtx objects, whose trace
        # ID / phase stamps the status endpoint reads.  The sync hot
        # path pays nothing.
        self.inflight: dict = {}
        # driver task per async in-flight query (same key): overload
        # shedding must be able to cancel the work it refuses, not just
        # answer for it (AdmissionControl.shed_overflow)
        self.inflight_tasks: dict = {}
        # Overload admission control (binder_tpu/policy/admission.py),
        # installed by BinderServer: bounds the in-flight table with
        # oldest-shed.  None = unbounded (the classic behavior).
        self.admission = None
        # Response rate limiting (binder_tpu/policy/rrl.py), installed
        # by BinderServer: per-client-prefix slip/drop at the UDP
        # ingress, judged before decode.  None = unlimited.
        self.rrl = None
        # Optional flight recorder (installed by BinderServer): the
        # engine's error path records resolver-error events on it.
        self.recorder = None
        # live BalancerLink objects receiving gen/invalidate broadcasts
        # (dict for cheap membership + stable iteration order)
        self._balancer_writers: dict = {}
        self._gen_dirty = False
        self._pending_inval: set = set()    # tag wires awaiting broadcast
        self._last_gen_sent: Optional[int] = None
        # Direct-return negotiation switch: announce the capability on
        # every balancer link so a capable balancer passes its client
        # socket.  BINDER_NO_DIRECT_RETURN=1 keeps the classic pure
        # relay — the A/B lever for tests and the bench's relay arm.
        self.balancer_direct_return = os.environ.get(
            "BINDER_NO_DIRECT_RETURN", "") not in ("1", "true", "yes")

    # -- shared query dispatch --
    #
    # The on_query hook is a *synchronous* callable returning either None
    # (query fully handled — the cache-hit hot path, no task overhead) or
    # an awaitable for work that needs real I/O (the recursion path),
    # which is then driven by a task.

    def _dispatch(self, request: Message, src: Tuple[str, int],
                  protocol: str, send: Callable[[bytes], None],
                  client_transport: Optional[str] = None,
                  raw: Optional[bytes] = None,
                  ctx_box: Optional[list] = None) -> None:
        query = QueryCtx(request, src, protocol, send,
                         client_transport=client_transport, raw=raw)
        if ctx_box is not None:
            # transports that need per-response state (the balancer's
            # do-not-store marker) observe the context through this box
            ctx_box.append(query)
        if self.on_query is None:
            query.set_error(Rcode.NOTIMP)
            query.respond()
            return
        try:
            pending = self.on_query(query)
        except Exception as e:
            self._on_query_error(query, e)
            return
        if pending is None:
            self._after(query)
            return
        self.inflight[id(query)] = query
        if pending is not HANDLED_ASYNC:
            task = asyncio.ensure_future(self._run_async(query, pending))
            self._tasks.add(task)
            self.inflight_tasks[id(query)] = task
            task.add_done_callback(self._tasks.discard)
        # overload admission: past the cap, the OLDEST in-flight query
        # is shed (immediate well-formed REFUSED + task cancel) so the
        # table bounds memory and upstream fan-out — a storm of stuck
        # forwards can never grow it without bound
        adm = self.admission
        if adm is not None and len(self.inflight) > adm.max_inflight:
            adm.shed_overflow(self)

    async def _run_async(self, query: QueryCtx, pending) -> None:
        try:
            await pending
        except Exception as e:
            self._on_query_error(query, e)
            return
        self._after(query)

    def _on_query_error(self, query: QueryCtx, e: Exception) -> None:
        self.inflight.pop(id(query), None)
        self.inflight_tasks.pop(id(query), None)
        if self.recorder is not None:
            self.recorder.record(
                "resolver-error", trace=query.trace_id,
                name=query.name(), qtype=query.qtype_name(),
                error=f"{type(e).__name__}: {e}")
        if isinstance(e, OSError) and e.errno == errno.EHOSTUNREACH:
            # asymmetric routing — log and carry on (lib/server.js:593-607)
            self.log.error("cannot reply to DNS traffic: "
                           "is there asymmetric routing?")
            return
        self.log.error("query handler failed", exc_info=e)
        if not query.responded:
            # drop any half-built (possibly unencodable) answer set —
            # reset_sections keeps the EDNS echo, so the SERVFAIL
            # carries the query's EDNS posture (RFC 6891 conformance,
            # pinned by tests/test_recursion.py)
            query.reset_sections()
            query.set_error(Rcode.SERVFAIL)
            try:
                query.respond()
            except OSError:
                pass

    def _after(self, query: QueryCtx) -> None:
        self.inflight.pop(id(query), None)
        self.inflight_tasks.pop(id(query), None)
        if query.after_done:
            return   # already metered (overload shed answered for it)
        query.after_done = True
        if self.on_after is not None and query.responded:
            try:
                self.on_after(query)
            except Exception:
                self.log.exception("after hook failed")

    # Decode cache: resolvers re-ask the same names constantly, and two
    # queries for the same name/type/flags differ only in the 2-byte id.
    # Keyed on the wire bytes minus the id; entries are treated as
    # immutable templates (the query path never mutates the request).
    _DECODE_CACHE_MAX = 4096
    # legitimate queries are tiny; anything larger is not worth pinning
    _CACHEABLE_QUERY_MAX = 320

    def _decode_query(self, data: bytes) -> Message:
        key = data[2:]
        tmpl = self._decode_cache.get(key)
        if tmpl is not None:
            # hand-rolled shallow copy: dataclasses.replace() re-runs the
            # generated __init__ (every field as kwarg) and costs ~7µs on
            # this exact hot line; Message is a plain (non-slots)
            # dataclass, so a __dict__ copy is equivalent
            new = Message.__new__(Message)
            new.__dict__.update(tmpl.__dict__)
            new.id = struct.unpack_from(">H", data, 0)[0]
            return new
        msg = Message.decode(data)
        if (len(data) <= self._CACHEABLE_QUERY_MAX
                and not msg.qr and msg.opcode == 0
                and len(msg.questions) == 1
                and not msg.answers and not msg.authorities
                # additionals: at most a bare OPT.  EDNS options (cookies,
                # padding) vary per packet, so such wires never repeat —
                # caching them only mints evict-pressure keys
                and len(msg.additionals) <= 1
                and all(isinstance(r, OPTRecord) and not r.has_options
                        for r in msg.additionals)):
            if len(self._decode_cache) >= self._DECODE_CACHE_MAX:
                # evict oldest insertion; wholesale clear() would flush
                # the hot templates along with the cold ones
                self._decode_cache.pop(next(iter(self._decode_cache)))
            self._decode_cache[key] = msg
        return msg

    def _fp_call(self, entry, payload: bytes, src, protocol: str):
        """Shared plumbing for the socket-free native serve entries:
        gate check, generation fetch, logged-posture signature (src
        rides along ONLY when the log ring is armed, so an older
        compiled extension's 3-arg form keeps working), and the
        TypeError/ValueError fallback.  Returns the entry's result, or
        None when the path is unavailable/declined."""
        if (self.fastpath is None or entry is None
                or (self.fastpath_gate is not None
                    and not self.fastpath_gate())):
            return None
        try:
            gen = self.fastpath_gen() if self.fastpath_gen else 0
            if self.fastpath_log_flush is not None:
                return entry(self.fastpath, payload, gen, src[0], src[1],
                             protocol)
            return entry(self.fastpath, payload, gen)
        except (TypeError, ValueError):
            return None

    def _serve_frames_bulk(self, buf: bytes, src):
        """Bulk native TCP-frame serve (``fastpath_serve_frames``):
        every complete frame in ``buf`` the C cache/zone can answer is
        served and framed back as one block.  Returns
        ``(resp_block, consumed, misses)`` or None when the native path
        is unavailable/declined.  The one call site is the stream
        lane's feed loop (dns/stream.py)."""
        return self._fp_call(_fp_serve_frames, buf, src, "tcp")

    def _handle_raw(self, data: bytes, src: Tuple[str, int],
                    protocol: str, send: Callable[[bytes], None],
                    client_transport: Optional[str] = None,
                    ctx_box: Optional[list] = None,
                    fastpath_checked: bool = False) -> None:
        # Response rate limiting at the UDP ingress, before decode and
        # before any lane can spend work on the packet: a flooded
        # prefix gets a TC slip or silence at raw-bytes cost.  While
        # the limiter is hot the fastpath gate (BinderServer) is shut,
        # so every direct-UDP packet surfaces here for judgment.  The
        # TCP lane is exempt by design — a spoofed source cannot
        # complete a handshake, and slips exist to push real clients
        # to TCP.
        rrl = self.rrl
        if rrl is not None and protocol == "udp":
            verdict = rrl.decide(src[0])
            if verdict != rrl.SEND:
                if verdict == rrl.SLIP:
                    resp = rrl.slip_reply(data)
                    if resp is not None:
                        try:
                            send(resp)
                        except OSError:
                            pass
                return
        elif rrl is not None and protocol == "tcp":
            # adaptive-bucket liveness evidence: a TCP query reaching
            # the serve path at all proves a completed handshake — the
            # one thing a spoofed source can never do.  While the
            # limiter is hot the fastpath gate is shut, so exactly the
            # TCP retries that matter (slipped clients coming back)
            # surface here.
            rrl.note_tcp(src[0])
        # Native answer-cache/zone serve for the lanes that have no C
        # drain of their own — TCP and the balancer socket.  Direct-UDP
        # packets reaching here already missed inside fastpath_drain,
        # and TCP payloads surfaced by the bulk frame serve arrive with
        # fastpath_checked=True — a second lookup would be pure waste.
        # Correct for every lane: entries hold only untruncated
        # responses and decline when the assembled wire would exceed
        # the query's advertised ceiling, so a TCP serve can never
        # differ from the Python path's.
        if protocol != "udp" and not fastpath_checked:
            resp = self._fp_call(_fp_serve_wire, data, src, protocol)
            if resp is not None:
                try:
                    send(resp)
                except OSError:
                    pass
                return
        lane = self.raw_lane
        if lane is not None:
            try:
                if lane(data, src, protocol, send, client_transport):
                    return
            except Exception:
                # the lane assembles before it sends, so falling through
                # re-processes the query from scratch safely
                self.log.exception("raw lane failed; using generic path")
        try:
            request = self._decode_query(data)
        except WireError as e:
            self.log.debug("dropping malformed packet from %s: %s", src, e)
            if len(data) >= 2:
                qid = struct.unpack_from(">H", data, 0)[0]
                resp = Message(id=qid, qr=True, rcode=Rcode.FORMERR)
                try:
                    send(resp.encode())
                except OSError:
                    pass
            return
        if request.qr:
            return  # not a query
        self._dispatch(request, src, protocol, send, client_transport,
                       raw=data, ctx_box=ctx_box)

    # -- UDP --

    # Packets drained per readiness callback: bounds event-loop
    # starvation of timers/TCP under sustained UDP flood.
    _UDP_BURST = 128

    async def listen_udp(self, address: str, port: int,
                         announce: bool = True,
                         reuse_port: bool = False) -> int:
        """Direct add_reader recv/send loop.

        asyncio's DatagramTransport costs ~15µs/packet in protocol
        plumbing (buffer management, flow control, call_soon hops) that a
        DNS responder doesn't need; reading the socket ourselves roughly
        doubles single-process throughput.  Send errors are tolerated
        best-effort like the reference (EHOSTUNREACH etc.,
        lib/server.js:593-607) — UDP clients retry.

        ``announce=False`` defers the "service started" log line — the
        ephemeral pair bind (BinderServer.start) must not advertise a
        port it may yet release and redraw: harnesses watch that line,
        and one observed CI failure latched a redrawn (dead) port."""
        loop = asyncio.get_running_loop()
        fam = socket.AF_INET6 if ":" in address else socket.AF_INET
        sock = socket.socket(fam, socket.SOCK_DGRAM)
        # no SO_REUSEADDR: UDP has no TIME_WAIT to work around, and on
        # Linux the option would let another local process bind a
        # more-specific address on the same port and divert queries
        # (the reason asyncio removed it for datagram endpoints).
        # SO_REUSEPORT is the deliberate exception — shard mode binds N
        # worker sockets on ONE port so the kernel's 4-tuple hash
        # balances queries across processes (same-UID only, so the
        # hijack concern above does not apply).
        if reuse_port:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        # absorb bursts while the event loop is busy with other work
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 1 << 20)
        except OSError:
            pass
        sock.setblocking(False)
        sock.bind((address, port))

        handle_raw = self._handle_raw
        recvfrom = sock.recvfrom
        sendto = sock.sendto
        log = self.log
        burst = self._UDP_BURST

        if _fastio is not None:
            on_readable = self._batched_udp_reader(sock)
        else:
            def on_readable() -> None:
                for _ in range(burst):
                    try:
                        data, addr = recvfrom(65535)
                    except (BlockingIOError, InterruptedError):
                        return
                    except OSError as e:
                        log.error("UDP socket error: %s", e)
                        return

                    def send(wire: bytes, _addr=addr) -> None:
                        try:
                            sendto(wire, _addr)
                        except OSError as e:
                            # best-effort: full socket buffer or
                            # unreachable client must not take down
                            # serving
                            log.debug("UDP send to %s failed: %s",
                                      _addr, e)

                    handle_raw(data, (addr[0], addr[1]), "udp", send)

        loop.add_reader(sock.fileno(), on_readable)
        self._udp_socks.append((loop, sock))
        actual = sock.getsockname()[1]
        if announce:
            self.announce_udp(address, actual)
        return actual

    def announce_udp(self, address: str, port: int) -> None:
        self.log.info("UDP DNS service started on %s:%d", address, port)

    def announce_tcp(self, address: str, port: int) -> None:
        self.log.info("TCP DNS service started on %s:%d", address, port)

    def close_udp_listener(self, port: int) -> None:
        """Tear down one bound UDP listener.  Used by the paired-bind
        retry in ``BinderServer.start``: with ``port=0`` the kernel
        picks the UDP port first, and when that number turns out to be
        occupied on TCP the draw must be released and repeated."""
        for i, (loop, sock) in enumerate(self._udp_socks):
            try:
                bound = sock.getsockname()[1]
            except OSError:
                continue
            if bound == port:
                try:
                    loop.remove_reader(sock.fileno())
                except (OSError, ValueError):
                    pass
                sock.close()
                del self._udp_socks[i]
                return

    def note_late_drops(self, n: int) -> None:
        """Account late (async-completed) UDP responses dropped at a
        full send buffer: monotonic counter + metrics child
        (binder_udp_late_drops_total) + a rate-limited flight event —
        at production scale a silent drop path is an invisible SLO
        leak, so the evidence must be scrapeable (ISSUE 7 satellite)."""
        if n <= 0:
            return
        self.udp_late_drops += n
        if self.late_drop_counter is not None:
            self.late_drop_counter.inc(n)
        self.log.debug("dropped %d late UDP responses "
                       "(send buffer full)", n)
        if self.recorder is not None:
            now = time.monotonic()
            if (now - self._late_drop_event_last
                    >= self.LATE_DROP_EVENT_WINDOW_S):
                self._late_drop_event_last = now
                self.recorder.record("udp-late-drop", dropped=n,
                                     total=self.udp_late_drops)

    def _batched_udp_reader(self, sock: socket.socket) -> Callable[[], None]:
        """recvmmsg/sendmmsg datapath (native/fastio/fastio.c).

        Up to 64 datagrams move per kernel crossing instead of one; on the
        single-core deployment unit (the reference scales by adding
        processes, boot/setup.sh:145-149, not threads) per-packet syscall
        overhead is the throughput floor, and batching roughly halves it.
        Responses produced synchronously during the drain are flushed as
        one sendmmsg; responses that arrive later (the recursion path) fall
        back to plain sendto."""
        handle_raw = self._handle_raw
        recv_batch = _fastio.recv_batch
        send_batch = _fastio.send_batch
        fp_drain = getattr(_fastio, "fastpath_drain", None)
        sendto = sock.sendto
        fd = sock.fileno()
        log = self.log
        burst = self._UDP_BURST
        batch_out: List[Optional[list]] = [None]  # non-None while draining
        # RRL duty-cycle sampling tick (see ResponseRateLimiter): a
        # cache-hit flood served entirely inside fastpath_drain would
        # never reach rrl.decide() to trip hot(), so while the gate is
        # open every Nth readiness event drains through Python with
        # decide() charging N tokens per sampled packet
        rrl_tick = [0]
        # Late (async-completed) responses — the recursion path — are
        # coalesced per event-loop pass into one sendmmsg instead of a
        # sendto syscall each: upstream answers arrive in batches on the
        # upstream socket, so their completions cluster in one pass.
        late_out: list = []

        def flush_late() -> None:
            out = late_out[:]
            late_out.clear()
            try:
                sent = send_batch(fd, out)
                if sent < len(out):
                    sent += send_batch(fd, out[sent:])
                    if sent < len(out):
                        self.note_late_drops(len(out) - sent)
            except OSError as e:
                log.error("batched late UDP send failed: %s", e)
                self.note_late_drops(len(out))

        def send_late(wire: bytes, addr) -> None:
            if not late_out:
                try:
                    asyncio.get_running_loop().call_soon(flush_late)
                except RuntimeError:
                    try:
                        sendto(wire, addr)
                    except OSError as e:
                        log.debug("UDP send to %s failed: %s", addr, e)
                    return
            late_out.append((wire, addr))

        def on_readable() -> None:
            out: list = []
            batch_out[0] = out
            # fast path on/off is decided once per readiness event — the
            # gate (query-log / probe state) can flip at runtime
            fp = self.fastpath
            use_fp = (fp is not None and fp_drain is not None
                      and (self.fastpath_gate is None
                           or self.fastpath_gate()))
            fp_gen = self.fastpath_gen
            rrl = self.rrl
            if rrl is not None:
                rrl.sample_cost = 1.0
                if use_fp:
                    rrl_tick[0] += 1
                    if rrl_tick[0] >= rrl.FASTPATH_SAMPLE_EVERY:
                        rrl_tick[0] = 0
                        use_fp = False
                        rrl.sample_cost = float(rrl.FASTPATH_SAMPLE_EVERY)
            try:
                drained = 0
                while drained < burst:
                    served = 0
                    try:
                        if use_fp:
                            msgs, served = fp_drain(
                                fp, fd, fp_gen() if fp_gen else 0, 64)
                        else:
                            msgs = recv_batch(fd, 64)
                    except OSError as e:
                        log.error("UDP socket error: %s", e)
                        break
                    if not msgs and not served:
                        break
                    drained += len(msgs) + served
                    for data, addr in msgs:
                        def send(wire: bytes, _addr=addr) -> None:
                            cur = batch_out[0]
                            if cur is not None:
                                cur.append((wire, _addr))
                            else:   # late (async) response
                                send_late(wire, _addr)
                        try:
                            handle_raw(data, addr, "udp", send)
                        except Exception:
                            # isolate per packet, like the plain path's
                            # one-callback-per-packet structure: a bug on
                            # one query must not abandon the drain or the
                            # flush of other clients' responses
                            log.exception("unhandled error processing "
                                          "packet from %s", addr)
                    if len(msgs) + served < 64:
                        break
            finally:
                # flush in finally so responses already produced are
                # never lost to an unexpected escape above
                batch_out[0] = None
                if out:
                    try:
                        sent = send_batch(fd, out)
                        if sent < len(out):
                            # socket buffer full: one retry, then drop
                            # (UDP clients retransmit; blocking here
                            # would stall the event loop for every other
                            # client)
                            sent += send_batch(fd, out[sent:])
                            if sent < len(out):
                                log.debug("dropped %d UDP responses "
                                          "(send buffer full)",
                                          len(out) - sent)
                    except OSError as e:
                        log.error("batched UDP send failed: %s", e)
                log_flush = self.fastpath_log_flush
                if use_fp and log_flush is not None:
                    try:
                        log_flush()
                    except Exception:
                        log.exception("query-log ring drain failed")

        return on_readable

    # -- TCP (2-byte length framing, RFC 1035 §4.2.2) --
    #
    # The stream lane runs on a raw accept loop + per-connection
    # readiness callbacks (dns/stream.py TcpConn), not
    # asyncio.start_server: protocol/StreamReader/StreamWriter/task
    # creation per connection was the dominant cost of every fresh
    # connection (tcp1 ~137µs, the tc=1 UDP→TCP retry flow 10.8ms p50
    # in BENCH_r05).  With TCP_DEFER_ACCEPT the first frame normally
    # rides the accept-readiness event, so a one-shot client is served
    # inside the accept callback — one loop iteration end to end.

    #: seconds a dataless connection may sit in the kernel's deferred-
    #: accept queue before being surfaced anyway (Linux rounds up to
    #: SYN-ACK retransmission boundaries).  Short enough that a patient
    #: legitimate client only pays ~1s of first-byte latency; long
    #: enough that connect-flood noise never occupies a connection slot.
    TCP_DEFER_ACCEPT_S = 1
    #: connections accepted per readiness callback — bounds event-loop
    #: starvation under an accept flood, like _UDP_BURST for datagrams
    _ACCEPT_BURST = 64

    async def listen_tcp(self, address: str, port: int,
                         announce: bool = True,
                         reuse_port: bool = False) -> int:
        loop = asyncio.get_running_loop()
        fam = socket.AF_INET6 if ":" in address else socket.AF_INET
        lsock = socket.socket(fam, socket.SOCK_STREAM)
        try:
            lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if reuse_port:
                # shard mode: the kernel spreads incoming connections
                # across every worker listening on this port
                lsock.setsockopt(socket.SOL_SOCKET,
                                 socket.SO_REUSEPORT, 1)
            lsock.setblocking(False)
            lsock.bind((address, port))
            lsock.listen(1024)
            # accept fast path: wake only when the first frame's bytes
            # are already in the socket buffer (guarded: not every
            # platform has the option, and serving must not depend on it)
            try:
                lsock.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_DEFER_ACCEPT,
                                 self.TCP_DEFER_ACCEPT_S)
            except (AttributeError, OSError):
                pass
        except OSError:
            # bind/listen failure (the pair-bind redraw path): leave no
            # socket behind
            lsock.close()
            raise
        loop.add_reader(lsock.fileno(), self._on_accept_ready, lsock,
                        loop)
        self._tcp_listeners.append((loop, lsock))
        if self._tcp_sweep_handle is None and self.tcp_idle_timeout:
            # ONE idle sweep for the whole connection table (vs a timer
            # per connection): granularity T/4 keeps worst-case
            # overstay at ~T/4 past the deadline
            interval = max(0.05, min(self.tcp_idle_timeout / 4.0, 5.0))
            self._tcp_sweep_handle = loop.call_later(
                interval, self._sweep_idle_tcp, loop, interval)
        actual = lsock.getsockname()[1]
        if announce:
            self.announce_tcp(address, actual)
        return actual

    def _on_accept_ready(self, lsock: socket.socket, loop) -> None:
        stats = self.tcp_stats
        for _ in range(self._ACCEPT_BURST):
            try:
                sock, peer = lsock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                self.log.error("TCP accept failed: %s", e)
                return
            stats.accepts += 1
            if len(self._tcp_conns) >= self.max_tcp_conns:
                # at the connection cap: refuse the newcomer outright
                # (the idle sweep guarantees slots recycle, so a
                # slowloris herd can't pin the front end shut for long)
                self._refuse_at_cap(sock, peer, loop)
                continue
            sock.setblocking(False)
            # (TCP_NODELAY is armed lazily by TcpConn — at promotion,
            # or as soon as a second write becomes possible.  A
            # one-shot client gets exactly one response write on a
            # fresh connection, which Nagle sends immediately anyway,
            # so the fast path skips the syscall.)
            TcpConn(self, sock, peer, loop).start()

    def _refuse_at_cap(self, sock: socket.socket, peer, loop) -> None:
        self.tcp_cap_refusals += 1
        self._cap_log_pending += 1
        now = loop.time()
        if now - self._cap_log_last >= 5.0:
            # a connect flood at the cap must not become a log flood:
            # refusals log at most once per interval, with the count
            self.log.warning(
                "TCP connection cap (%d) reached, refused %d "
                "connection(s) since last report (latest: %s; full "
                "count in binder_tcp_cap_refusals)",
                self.max_tcp_conns, self._cap_log_pending, peer[0])
            self._cap_log_last = now
            self._cap_log_pending = 0
        try:
            sock.close()
        except OSError:
            pass

    def _sweep_idle_tcp(self, loop, interval: float) -> None:
        self._tcp_sweep_handle = None
        now = loop.time()
        for conn in list(self._tcp_conns):
            deadline = conn.deadline
            if deadline is not None and now > deadline:
                self.tcp_stats.idle_timeouts += 1
                self.log.debug("closing idle TCP connection from %s",
                               conn.peer[0])
                conn.close()
        if self._tcp_listeners or self._tcp_conns:
            self._tcp_sweep_handle = loop.call_later(
                interval, self._sweep_idle_tcp, loop, interval)

    def tcp_introspect(self) -> dict:
        """The ``/status`` ``tcp`` section: live connection-table state
        plus the stream-lane counters (docs/observability.md)."""
        out = self.tcp_stats.snapshot()
        out.update({
            "open_conns": len(self._tcp_conns),
            "max_conns": self.max_tcp_conns,
            "idle_timeout_seconds": float(self.tcp_idle_timeout or 0.0),
            "max_write_buffer": self.max_tcp_write_buffer,
            "cap_refusals": self.tcp_cap_refusals,
        })
        return out

    # -- balancer backend socket (docs/balancer-protocol.md) --

    async def listen_balancer(self, path: str) -> None:
        # raw listener + raw per-link sockets, not asyncio streams: the
        # direct-return fd pass arrives as SCM_RIGHTS ancillary data,
        # which the stream protocol machinery silently discards
        loop = asyncio.get_running_loop()
        lsock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            lsock.setblocking(False)
            lsock.bind(path)
            lsock.listen(64)
        except OSError:
            lsock.close()
            raise
        loop.add_reader(lsock.fileno(), self._on_balancer_accept, lsock,
                        loop)
        self._unix_servers.append((loop, lsock, path))
        self.log.info("balancer service started on %s", path)

    def _on_balancer_accept(self, lsock: socket.socket, loop) -> None:
        for _ in range(self._ACCEPT_BURST):
            try:
                sock, _peer = lsock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                self.log.error("balancer accept failed: %s", e)
                return
            sock.setblocking(False)
            BalancerLink(self, sock, loop).start()

    def notify_mutation(self) -> None:
        """Broadcast a fresh generation frame to every balancer link,
        coalesced to one frame per event-loop turn (a session rebuild
        bumps the generation once per mirrored node)."""
        if self._gen_dirty or not self._balancer_writers \
                or self.gen_source is None:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return   # no loop: no balancer link is being served either
        self._gen_dirty = True
        loop.call_soon(self._send_gen_frames)

    def notify_invalidate(self, tag_wires) -> None:
        """Broadcast per-name invalidate frames (opcode 1) to every
        balancer link, coalesced per event-loop turn like the generation
        report — and through the same ordered write path, so a response
        computed under pre-mutation data (whose write task exists before
        the mutation ran) always reaches the balancer before the frame
        that would invalidate it."""
        if not self._balancer_writers:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return   # no loop: no balancer link is being served either
        schedule = not self._pending_inval and not self._gen_dirty
        self._pending_inval.update(tag_wires)
        if schedule and self._pending_inval:
            loop.call_soon(self._send_gen_frames)

    def _send_gen_frames(self) -> None:
        gen_dirty = self._gen_dirty
        self._gen_dirty = False
        pending = self._pending_inval
        self._pending_inval = set()
        frame = b""
        if gen_dirty and self.gen_source is not None:
            # mutations mark dirty but the reported value is the epoch,
            # which only moves on rebuilds — skip the no-op frame the
            # balancer would ignore anyway
            val = self.gen_source()
            if val != self._last_gen_sent:
                frame += pack_gen_frame(val)
                self._last_gen_sent = val
        for tag in sorted(pending):
            frame += pack_invalidate_frame(tag)
        if not frame:
            return
        for link in list(self._balancer_writers):
            # the frame rides the same append-ordered write buffer as
            # relay responses: a response computed under the OLD
            # generation was appended synchronously when its send
            # callback ran — before the call_soon that brought us here
            # could fire — so the balancer never tags a stale response
            # with the new generation
            link.send_frame(frame)

    # -- lifecycle --

    async def quiesce(self, timeout: float = 5.0) -> int:
        """Graceful stop-accepting for the rolling drain-and-replace
        cycle (shard supervisor, docs/operations.md "Rolling
        upgrade"): stop taking NEW work, serve out what is already
        here, then leave the ``SO_REUSEPORT`` group.

        Order matters: the accept paths close first (new TCP clients
        re-hash to the surviving group members immediately), then the
        UDP read loop stops and the datagrams the kernel already
        queued to this socket — which would be silently dropped at
        close — are served out synchronously before the socket closes
        and its hash share moves over.  Finally a bounded wait lets
        async in-flight queries finish and one settle tick lets the
        stream lane's write coalescing flush.  Returns the number of
        in-flight queries still pending at the deadline (0 == clean
        drain)."""
        for loop, lsock in self._tcp_listeners:
            try:
                loop.remove_reader(lsock.fileno())
            except (OSError, ValueError):
                pass
            lsock.close()
        self._tcp_listeners.clear()
        for loop, lsock, _path in self._unix_servers:
            try:
                loop.remove_reader(lsock.fileno())
            except (OSError, ValueError):
                pass
            lsock.close()
        self._unix_servers.clear()
        for loop, sock in self._udp_socks:
            try:
                loop.remove_reader(sock.fileno())
            except (OSError, ValueError):
                pass
            while True:
                try:
                    data, addr = sock.recvfrom(65535)
                except (BlockingIOError, InterruptedError):
                    break
                except OSError:
                    break

                def send(wire: bytes, _sock=sock, _addr=addr) -> None:
                    try:
                        _sock.sendto(wire, _addr)
                    except OSError:
                        pass

                self._handle_raw(data, (addr[0], addr[1]), "udp", send)
            # leaving the group NOW keeps the unread window to the
            # microseconds between the drain loop and this close; an
            # async in-flight UDP answer past this point is best-effort
            # (its reply socket is gone), matching the sync-dominated
            # shard serving profile
            sock.close()
        self._udp_socks.clear()
        deadline = time.monotonic() + timeout
        while self.inflight and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        # one settle pass for the per-tick TCP write coalescing
        await asyncio.sleep(0.05)
        return len(self.inflight)

    async def close(self) -> None:
        for loop, sock in self._udp_socks:
            try:
                loop.remove_reader(sock.fileno())
            except (OSError, ValueError):
                pass
            sock.close()
        if self._tcp_sweep_handle is not None:
            self._tcp_sweep_handle.cancel()
            self._tcp_sweep_handle = None
        for loop, lsock in self._tcp_listeners:
            try:
                loop.remove_reader(lsock.fileno())
            except (OSError, ValueError):
                pass
            lsock.close()
        for w in list(self._conns):
            w.close()
        for loop, lsock, path in self._unix_servers:
            try:
                loop.remove_reader(lsock.fileno())
            except (OSError, ValueError):
                pass
            # note: the path is NOT unlinked here — supervisor SIGTERM
            # semantics own the unlink (main.py), matching the old
            # stream-server behavior callers test against
            lsock.close()
        for task in list(self._tasks):
            task.cancel()
        self._udp_socks.clear()
        self._tcp_listeners.clear()
        self._unix_servers.clear()
