"""DNS wire-format codec.

From-scratch implementation of the DNS message format (RFC 1035, plus SRV
RFC 2782 and EDNS0 RFC 6891) — the layer the reference delegates to the
external ``mname`` npm package (reference ``package.json:14``, consumed at
``lib/server.js:19-22,443-446``).  The rebuild owns this layer per SURVEY
§7.1 step 1.

Design notes:
- Encoding uses full name compression (suffix-pointer table) — answers for
  service records repeat the query name many times, so compression directly
  cuts response bytes on the hot path.
- Decoding is strict about bounds and pointer loops (a malformed packet must
  never hang or over-read; compare the reference's zklog.c overflow-checked
  walks for the house style).
- Record classes mirror the reference's record typology: A / AAAA / SRV /
  PTR / SOA / TXT / CNAME / NS / OPT (mname's ARecord/SRVRecord/PTRRecord/
  SOARecord at ``lib/server.js:19-22`` plus the client-side types recursion
  rebuilds at ``lib/recursion.js:299-323``).
"""
from __future__ import annotations

import dataclasses
import ipaddress
import socket as _socket
import struct
from typing import ClassVar, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Constants


class Type:
    A = 1
    NS = 2
    CNAME = 5
    SOA = 6
    PTR = 12
    MX = 15
    TXT = 16
    AAAA = 28
    SRV = 33
    OPT = 41
    ANY = 255

    _names: ClassVar[Dict[int, str]] = {}

    @classmethod
    def name(cls, code: int) -> str:
        if not cls._names:
            cls._names = {
                v: k for k, v in vars(cls).items()
                if isinstance(v, int) and k.isupper()
            }
        return cls._names.get(code, f"TYPE{code}")


class Class:
    IN = 1
    CH = 3
    ANY = 255


class Rcode:
    NOERROR = 0
    FORMERR = 1
    SERVFAIL = 2
    NXDOMAIN = 3
    NOTIMP = 4
    REFUSED = 5

    _names: ClassVar[Dict[int, str]] = {}

    @classmethod
    def name(cls, code: int) -> str:
        if not cls._names:
            cls._names = {
                v: k for k, v in vars(cls).items()
                if isinstance(v, int) and k.isupper()
            }
        return cls._names.get(code, f"RCODE{code}")


class Opcode:
    QUERY = 0
    IQUERY = 1
    STATUS = 2
    NOTIFY = 4
    UPDATE = 5


MAX_NAME_LEN = 255
MAX_LABEL_LEN = 63
MAX_UDP_PAYLOAD = 512   # classic; EDNS extends
MAX_EDNS_PAYLOAD = 4096  # ceiling we honor from an OPT advertisement


class WireError(Exception):
    """Malformed DNS wire data."""


# ---------------------------------------------------------------------------
# Name encoding / decoding


def normalize_name(name: str) -> str:
    """Lowercase and strip the trailing dot ('Foo.Com.' -> 'foo.com')."""
    n = name.strip().lower()
    if n.endswith("."):
        n = n[:-1]
    return n


def encode_name(name: str, buf: bytearray,
                offsets: Optional[Dict[str, int]] = None) -> None:
    """Append *name* to *buf*, using/recording compression offsets.

    *offsets* maps a normalized suffix string ('foo.com') to the buffer
    offset where that suffix was first written.  Pointers may only target
    offsets < 0x4000 (14-bit), per RFC 1035 §4.1.4.
    """
    name = normalize_name(name)
    if name == "":
        buf.append(0)
        return
    if len(name) > MAX_NAME_LEN - 1:
        raise WireError(f"name too long: {name!r}")
    labels = name.split(".")
    for i, label in enumerate(labels):
        if not label or len(label) > MAX_LABEL_LEN:
            raise WireError(f"bad label in name {name!r}")
        suffix = ".".join(labels[i:])
        if offsets is not None:
            at = offsets.get(suffix)
            if at is not None:
                buf += struct.pack(">H", 0xC000 | at)
                return
            if len(buf) < 0x4000:
                offsets[suffix] = len(buf)
        raw = label.encode("ascii")
        buf.append(len(raw))
        buf += raw
    buf.append(0)


def decode_name(data: bytes, off: int) -> Tuple[str, int]:
    """Decode a (possibly compressed) name at *off*.

    Returns (name, offset-after-name-in-original-stream).
    """
    labels: List[str] = []
    jumps = 0
    end: Optional[int] = None  # offset after the first pointer (or terminator)
    total = 0
    pos = off
    while True:
        if pos >= len(data):
            raise WireError("name runs past end of message")
        length = data[pos]
        if length & 0xC0 == 0xC0:
            if pos + 2 > len(data):
                raise WireError("truncated compression pointer")
            ptr = struct.unpack_from(">H", data, pos)[0] & 0x3FFF
            if end is None:
                end = pos + 2
            if ptr >= pos:
                raise WireError("forward/self compression pointer")
            jumps += 1
            if jumps > 128:
                raise WireError("compression pointer loop")
            pos = ptr
            continue
        if length & 0xC0:
            raise WireError(f"reserved label type 0x{length:02x}")
        pos += 1
        if length == 0:
            if end is None:
                end = pos
            break
        if pos + length > len(data):
            raise WireError("label runs past end of message")
        total += length + 1
        if total > MAX_NAME_LEN:
            raise WireError("decoded name too long")
        chunk = data[pos:pos + length]
        if not chunk.isascii():
            # Reject rather than replace: a U+FFFD-bearing name decodes
            # fine but can never re-encode (the question echo in every
            # REFUSED/FORMERR response would raise mid-respond), so
            # tolerating it here turns hostile bytes into a serve-path
            # exception.  Real clients put only ASCII (IDN is punycode)
            # on the wire; anything else earns the header-only FORMERR.
            raise WireError("non-ascii label")
        labels.append(chunk.decode("ascii").lower())
        pos += length
    return ".".join(labels), end


# ---------------------------------------------------------------------------
# Resource records


@dataclasses.dataclass
class Record:
    """Base resource record.  Subclasses define rtype + rdata codec."""
    name: str
    ttl: int
    rclass: int = Class.IN
    rtype: ClassVar[int] = 0

    def encode_rdata(self, buf: bytearray, offsets: Dict[str, int]) -> None:
        raise NotImplementedError

    @classmethod
    def decode_rdata(cls, data: bytes, off: int, rdlen: int,
                     name: str, ttl: int, rclass: int) -> "Record":
        raise NotImplementedError

    # -- shared plumbing --

    def encode(self, buf: bytearray, offsets: Dict[str, int]) -> None:
        encode_name(self.name, buf, offsets)
        buf += struct.pack(">HHI", self.rtype, self.rclass, self.ttl & 0xFFFFFFFF)
        len_at = len(buf)
        buf += b"\x00\x00"
        self.encode_rdata(buf, offsets)
        rdlen = len(buf) - len_at - 2
        struct.pack_into(">H", buf, len_at, rdlen)


@dataclasses.dataclass
class ARecord(Record):
    rtype: ClassVar[int] = Type.A
    address: str = "0.0.0.0"

    def encode_rdata(self, buf, offsets):
        # inet_aton is ~5x cheaper than ipaddress on this hot path, but
        # accepts legacy short/hex forms ("10.1", "0x7f.1") that would
        # silently encode a different address than stored — the ntoa
        # round-trip rejects anything but canonical dotted-quad
        try:
            packed = _socket.inet_aton(self.address)
        except (OSError, TypeError):
            raise WireError(f"bad A address {self.address!r}")
        if _socket.inet_ntoa(packed) != self.address:
            raise WireError(f"non-canonical A address {self.address!r}")
        buf += packed

    @classmethod
    def decode_rdata(cls, data, off, rdlen, name, ttl, rclass):
        if rdlen != 4:
            raise WireError("A rdata must be 4 bytes")
        return cls(name=name, ttl=ttl, rclass=rclass,
                   address=_socket.inet_ntoa(data[off:off + 4]))


@dataclasses.dataclass
class AAAARecord(Record):
    rtype: ClassVar[int] = Type.AAAA
    address: str = "::"

    def encode_rdata(self, buf, offsets):
        buf += ipaddress.IPv6Address(self.address).packed

    @classmethod
    def decode_rdata(cls, data, off, rdlen, name, ttl, rclass):
        if rdlen != 16:
            raise WireError("AAAA rdata must be 16 bytes")
        return cls(name=name, ttl=ttl, rclass=rclass,
                   address=str(ipaddress.IPv6Address(data[off:off + 16])))


@dataclasses.dataclass
class _NameRecord(Record):
    """Records whose rdata is a single domain name."""
    target: str = ""
    # RFC 3597 would forbid compressing rdata names for unknown types; for
    # these well-known types compression is standard.

    def encode_rdata(self, buf, offsets):
        encode_name(self.target, buf, offsets)

    @classmethod
    def decode_rdata(cls, data, off, rdlen, name, ttl, rclass):
        target, end = decode_name(data, off)
        if end > off + rdlen:
            raise WireError("rdata name runs past rdlen")
        return cls(name=name, ttl=ttl, rclass=rclass, target=target)


@dataclasses.dataclass
class PTRRecord(_NameRecord):
    rtype: ClassVar[int] = Type.PTR


@dataclasses.dataclass
class CNAMERecord(_NameRecord):
    rtype: ClassVar[int] = Type.CNAME


@dataclasses.dataclass
class NSRecord(_NameRecord):
    rtype: ClassVar[int] = Type.NS


@dataclasses.dataclass
class SRVRecord(Record):
    rtype: ClassVar[int] = Type.SRV
    priority: int = 0
    weight: int = 0
    port: int = 0
    target: str = ""

    def encode_rdata(self, buf, offsets):
        buf += struct.pack(">HHH", self.priority, self.weight, self.port)
        # RFC 2782 says the target must not be compressed; write it raw.
        encode_name(self.target, buf, None)

    @classmethod
    def decode_rdata(cls, data, off, rdlen, name, ttl, rclass):
        if rdlen < 7:
            raise WireError("SRV rdata too short")
        prio, weight, port = struct.unpack_from(">HHH", data, off)
        target, end = decode_name(data, off + 6)
        if end > off + rdlen:
            raise WireError("SRV target runs past rdlen")
        return cls(name=name, ttl=ttl, rclass=rclass, priority=prio,
                   weight=weight, port=port, target=target)


@dataclasses.dataclass
class SOARecord(Record):
    rtype: ClassVar[int] = Type.SOA
    mname: str = ""
    rname: str = ""
    serial: int = 0
    refresh: int = 0
    retry: int = 0
    expire: int = 0
    minimum: int = 0

    def encode_rdata(self, buf, offsets):
        encode_name(self.mname, buf, offsets)
        encode_name(self.rname, buf, offsets)
        buf += struct.pack(">IIIII", self.serial, self.refresh, self.retry,
                           self.expire, self.minimum)

    @classmethod
    def decode_rdata(cls, data, off, rdlen, name, ttl, rclass):
        mname, off2 = decode_name(data, off)
        rname, off3 = decode_name(data, off2)
        if off3 + 20 > off + rdlen:
            raise WireError("SOA rdata too short")
        serial, refresh, retry, expire, minimum = struct.unpack_from(
            ">IIIII", data, off3)
        return cls(name=name, ttl=ttl, rclass=rclass, mname=mname,
                   rname=rname, serial=serial, refresh=refresh, retry=retry,
                   expire=expire, minimum=minimum)


@dataclasses.dataclass
class TXTRecord(Record):
    rtype: ClassVar[int] = Type.TXT
    texts: Tuple[str, ...] = ()

    def encode_rdata(self, buf, offsets):
        for t in self.texts:
            raw = t.encode("utf-8")
            if len(raw) > 255:
                raise WireError("TXT string too long")
            buf.append(len(raw))
            buf += raw

    @classmethod
    def decode_rdata(cls, data, off, rdlen, name, ttl, rclass):
        texts: List[str] = []
        end = off + rdlen
        while off < end:
            n = data[off]
            off += 1
            if off + n > end:
                raise WireError("TXT string runs past rdata")
            texts.append(data[off:off + n].decode("utf-8", "replace"))
            off += n
        return cls(name=name, ttl=ttl, rclass=rclass, texts=tuple(texts))


@dataclasses.dataclass
class OPTRecord(Record):
    """EDNS0 pseudo-record (RFC 6891).  ttl field carries ext-rcode/flags."""
    rtype: ClassVar[int] = Type.OPT
    udp_payload_size: int = 1232
    ext_rcode: int = 0
    version: int = 0
    dnssec_ok: bool = False
    # options (cookies, padding, ...) are ignored semantically but their
    # presence matters to the decode cache: option bytes vary per packet,
    # so such requests can never be cache templates
    has_options: bool = False

    def encode(self, buf, offsets):
        buf.append(0)  # root name
        flags = (0x8000 if self.dnssec_ok else 0)
        ttl = (self.ext_rcode << 24) | (self.version << 16) | flags
        buf += struct.pack(">HHI", self.rtype, self.udp_payload_size, ttl)
        buf += b"\x00\x00"  # no options

    def encode_rdata(self, buf, offsets):  # pragma: no cover - unused
        pass

    @classmethod
    def from_wire(cls, name, ttl, rclass, rdata):
        return cls(
            name=name, ttl=0, rclass=Class.IN,
            udp_payload_size=rclass,
            ext_rcode=(ttl >> 24) & 0xFF,
            version=(ttl >> 16) & 0xFF,
            dnssec_ok=bool(ttl & 0x8000),
            has_options=bool(rdata),
        )


@dataclasses.dataclass
class RawRecord(Record):
    """Unknown rtype — rdata kept opaque (RFC 3597 behavior)."""
    rtype_code: int = 0
    rdata: bytes = b""

    @property
    def rtype(self):  # type: ignore[override]
        return self.rtype_code

    def encode(self, buf, offsets):
        encode_name(self.name, buf, offsets)
        buf += struct.pack(">HHI", self.rtype_code, self.rclass,
                           self.ttl & 0xFFFFFFFF)
        buf += struct.pack(">H", len(self.rdata))
        buf += self.rdata

    def encode_rdata(self, buf, offsets):  # pragma: no cover - unused
        pass


_RECORD_TYPES: Dict[int, type] = {
    Type.A: ARecord,
    Type.AAAA: AAAARecord,
    Type.PTR: PTRRecord,
    Type.CNAME: CNAMERecord,
    Type.NS: NSRecord,
    Type.SRV: SRVRecord,
    Type.SOA: SOARecord,
    Type.TXT: TXTRecord,
}


def _decode_record(data: bytes, off: int) -> Tuple[Record, int]:
    name, off = decode_name(data, off)
    if off + 10 > len(data):
        raise WireError("truncated record header")
    rtype, rclass, ttl, rdlen = struct.unpack_from(">HHIH", data, off)
    off += 10
    if off + rdlen > len(data):
        raise WireError("rdata runs past end of message")
    if rtype == Type.OPT:
        rec: Record = OPTRecord.from_wire(name, ttl, rclass,
                                          data[off:off + rdlen])
    else:
        cls = _RECORD_TYPES.get(rtype)
        if cls is None:
            rec = RawRecord(name=name, ttl=ttl, rclass=rclass,
                            rtype_code=rtype, rdata=bytes(data[off:off + rdlen]))
        else:
            rec = cls.decode_rdata(data, off, rdlen, name, ttl, rclass)
    return rec, off + rdlen


# ---------------------------------------------------------------------------
# Question + Message


@dataclasses.dataclass
class Question:
    name: str
    qtype: int
    qclass: int = Class.IN

    def encode(self, buf: bytearray, offsets: Dict[str, int]) -> None:
        encode_name(self.name, buf, offsets)
        buf += struct.pack(">HH", self.qtype, self.qclass)


@dataclasses.dataclass
class Message:
    id: int = 0
    qr: bool = False
    opcode: int = Opcode.QUERY
    aa: bool = False
    tc: bool = False
    rd: bool = False
    ra: bool = False
    rcode: int = Rcode.NOERROR
    questions: List[Question] = dataclasses.field(default_factory=list)
    answers: List[Record] = dataclasses.field(default_factory=list)
    authorities: List[Record] = dataclasses.field(default_factory=list)
    additionals: List[Record] = dataclasses.field(default_factory=list)

    def _flags(self) -> int:
        f = 0
        if self.qr:
            f |= 0x8000
        f |= (self.opcode & 0xF) << 11
        if self.aa:
            f |= 0x0400
        if self.tc:
            f |= 0x0200
        if self.rd:
            f |= 0x0100
        if self.ra:
            f |= 0x0080
        f |= self.rcode & 0xF
        return f

    def encode(self, max_size: Optional[int] = None) -> bytes:
        """Serialize with name compression.

        If *max_size* is given and the message exceeds it, answers are
        dropped and TC is set (UDP truncation semantics).
        """
        buf = bytearray()
        offsets: Dict[str, int] = {}
        buf += struct.pack(
            ">HHHHHH", self.id, self._flags(), len(self.questions),
            len(self.answers), len(self.authorities), len(self.additionals))
        for q in self.questions:
            q.encode(buf, offsets)
        for rec in self.answers:
            rec.encode(buf, offsets)
        for rec in self.authorities:
            rec.encode(buf, offsets)
        for rec in self.additionals:
            rec.encode(buf, offsets)
        if max_size is not None and len(buf) > max_size:
            # RFC 6891: keep the OPT pseudo-record in TC responses so EDNS
            # clients retain negotiated payload size on retry.
            opt = [r for r in self.additionals if isinstance(r, OPTRecord)]
            truncated = dataclasses.replace(
                self, tc=True, answers=[], authorities=[], additionals=opt)
            return truncated.encode(None)
        return bytes(buf)

    @classmethod
    def decode(cls, data: bytes) -> "Message":
        """Strict decode; raises WireError for ANYTHING malformed.

        The armor wrapper is the contract the serve lanes build on:
        every lane maps WireError to FORMERR-or-drop, so a decoder bug
        (struct.error, IndexError, a codec surprise) reached by a
        hostile frame must degrade to the same verdict instead of
        becoming an unhandled exception in a read loop.  The corpus
        replay in tests/test_hostile.py pins this."""
        try:
            return cls._decode(data)
        except WireError:
            raise
        except Exception as e:
            raise WireError(f"undecodable message "
                            f"({type(e).__name__}: {e})") from e

    @classmethod
    def _decode(cls, data: bytes) -> "Message":
        if len(data) < 12:
            raise WireError("message shorter than header")
        (mid, flags, qd, an, ns, ar) = struct.unpack_from(">HHHHHH", data, 0)
        msg = cls(
            id=mid,
            qr=bool(flags & 0x8000),
            opcode=(flags >> 11) & 0xF,
            aa=bool(flags & 0x0400),
            tc=bool(flags & 0x0200),
            rd=bool(flags & 0x0100),
            ra=bool(flags & 0x0080),
            rcode=flags & 0xF,
        )
        off = 12
        for _ in range(qd):
            name, off = decode_name(data, off)
            if off + 4 > len(data):
                raise WireError("truncated question")
            qtype, qclass = struct.unpack_from(">HH", data, off)
            off += 4
            msg.questions.append(Question(name=name, qtype=qtype, qclass=qclass))
        for _ in range(an):
            rec, off = _decode_record(data, off)
            msg.answers.append(rec)
        for _ in range(ns):
            rec, off = _decode_record(data, off)
            msg.authorities.append(rec)
        for _ in range(ar):
            rec, off = _decode_record(data, off)
            msg.additionals.append(rec)
        if off != len(data):
            # trailing bytes beyond the counted records: no legitimate
            # client produces these, and tolerating them lets attackers
            # mint unique cache keys from one query
            raise WireError(f"{len(data) - off} trailing bytes")
        return msg

    # -- convenience --

    @property
    def edns(self) -> Optional[OPTRecord]:
        # memoized: the serve path asks several times per query and
        # request additionals never change after decode (a request built
        # by hand must not grow an OPT after first access)
        try:
            return self._edns_memo
        except AttributeError:
            pass
        memo = None
        for rec in self.additionals:
            if isinstance(rec, OPTRecord):
                memo = rec
                break
        self._edns_memo = memo
        return memo

    def max_udp_payload(self) -> int:
        opt = self.edns
        if opt is not None and opt.udp_payload_size >= MAX_UDP_PAYLOAD:
            return min(opt.udp_payload_size, MAX_EDNS_PAYLOAD)
        return MAX_UDP_PAYLOAD


def skip_name(buf: bytes, off: int) -> Optional[int]:
    """Offset just past a wire name at ``off`` — labels walked, a
    compression pointer consumed as the 2-byte terminator it is; None
    on malformed/overrun.  Structural only (no decompression): used by
    consumers that forward or validate wires without decoding them."""
    n = len(buf)
    while True:
        if off >= n:
            return None
        b = buf[off]
        if b == 0:
            return off + 1
        if b & 0xC0 == 0xC0:
            return off + 2 if off + 2 <= n else None
        if b & 0xC0:
            return None
        off += 1 + b


def skip_record(buf: bytes, off: int) -> Optional[Tuple[int, int]]:
    """(next_offset, rtype) for the record at ``off``; None on bounds."""
    noff = skip_name(buf, off)
    if noff is None or noff + 10 > len(buf):
        return None
    rtype = (buf[noff] << 8) | buf[noff + 1]
    rdlen = (buf[noff + 8] << 8) | buf[noff + 9]
    end = noff + 10 + rdlen
    if end > len(buf):
        return None
    return end, rtype


def wire_walks(raw: bytes) -> bool:
    """True when the message's section counts walk the wire cleanly to
    its exact end — the structural validation applied to upstream
    responses before they can win a lookup (a full decode happens only
    on paths that need record objects)."""
    if len(raw) < 12:
        return False
    counts = ((raw[4] << 8) | raw[5], (raw[6] << 8) | raw[7],
              (raw[8] << 8) | raw[9], (raw[10] << 8) | raw[11])
    off = 12
    for _ in range(counts[0]):
        noff = skip_name(raw, off)
        if noff is None or noff + 4 > len(raw):
            return False
        off = noff + 4
    for _ in range(counts[1] + counts[2] + counts[3]):
        nxt = skip_record(raw, off)
        if nxt is None:
            return False
        off = nxt[0]
    return off == len(raw)


def patch_answer_wire(wire: bytes, qid: Optional[int] = None,
                      rd: Optional[bool] = None) -> bytes:
    """ID/flags patch for a precompiled response wire — the query-time
    half of the mutation-time pipeline (`resolver/precompile.py`).

    Precompiled wires are rendered canonically (id 0, RD clear); serving
    one to a live query is this patch plus the question-case echo the
    respond path already applies — never a re-encode.  The EDNS axis is
    handled by variant selection, not patching: the OPT echo sits at the
    head of the additionals section (`QueryCtx` appends it at
    construction, before any answer-derived additionals), so a
    with-EDNS wire is pre-rendered alongside the without-EDNS one
    rather than spliced per query.
    """
    b = bytearray(wire)
    if qid is not None:
        b[0] = (qid >> 8) & 0xFF
        b[1] = qid & 0xFF
    if rd is not None:
        if rd:
            b[2] |= 0x01
        else:
            b[2] &= 0xFE
    return bytes(b)


def make_query(name: str, qtype: int, *, qid: int = 0, rd: bool = False,
               edns_payload: Optional[int] = 1232) -> Message:
    """Build a standard query message (client side / tests)."""
    msg = Message(id=qid, rd=rd,
                  questions=[Question(name=normalize_name(name), qtype=qtype)])
    if edns_payload:
        msg.additionals.append(OPTRecord(name="", ttl=0,
                                         udp_payload_size=edns_payload))
    return msg


def reverse_name_for_ip(ip: str) -> str:
    """'10.1.2.3' -> '3.2.1.10.in-addr.arpa' (v6 -> ip6.arpa nibbles)."""
    addr = ipaddress.ip_address(ip)
    return addr.reverse_pointer


def ip_from_reverse_name(name: str) -> Optional[str]:
    """Parse 'd.c.b.a.in-addr.arpa' -> 'a.b.c.d', or ip6.arpa -> IPv6.

    Returns None if the name is not a well-formed reverse name (the caller
    decides the rcode policy — the reference REFUSES such queries,
    ``lib/server.js:71-103``).
    """
    n = normalize_name(name)
    if n.endswith(".in-addr.arpa"):
        parts = n[:-len(".in-addr.arpa")].split(".")
        if len(parts) != 4:
            return None
        try:
            octets = [int(p) for p in parts]
        except ValueError:
            return None
        if any(o < 0 or o > 255 for o in octets):
            return None
        if any(p != str(o) for p, o in zip(parts, octets)):
            return None  # reject leading zeros / weird forms
        return ".".join(str(o) for o in reversed(octets))
    if n.endswith(".ip6.arpa"):
        nibbles = n[:-len(".ip6.arpa")].split(".")
        if len(nibbles) != 32:
            return None
        if any(len(nib) != 1 or nib not in "0123456789abcdef"
               for nib in nibbles):
            return None
        hexstr = "".join(reversed(nibbles))
        groups = [hexstr[i:i + 4] for i in range(0, 32, 4)]
        return str(ipaddress.IPv6Address(":".join(groups)))
    return None
