"""Per-query context: request, response-under-construction, timers.

The mname-equivalent query object handed to the resolution layer (reference
mname's query, consumed at ``lib/server.js:471-507``).  Carries:

- the decoded request and the response being assembled,
- the client address (which for balancer-socket queries is the *original*
  client, not the balancer — SURVEY §2.2 L1),
- per-phase timers (reference ``query._stamp``, ``lib/server.js:476-483``),
- the structured-log context dict.

``respond()`` hands the finished response to the transport callback exactly
once; the server engine then emits the ``after`` event for metrics/logging
(reference ``lib/server.js:509-591``).
"""
from __future__ import annotations

import itertools
import os
import time
from typing import Callable, Dict, Optional, Tuple

from binder_tpu.dns.wire import (
    Message,
    OPTRecord,
    Record,
    Type,
)

_ECHO_OPT = OPTRecord(name="", ttl=0, udp_payload_size=1232)

# Per-query trace IDs: "<pid hex>-<seq hex>", unique within a process
# for the life of the counter and distinguishable across the N-process
# deployment unit.  itertools.count.__next__ is a single C call, so
# concurrent allocation (scrape threads, tests) can never hand two
# queries the same sequence number.
_TRACE_SEQ = itertools.count(1)
_TRACE_PREFIX = f"{os.getpid():x}-"


def next_trace_id() -> str:
    """Allocate a process-unique query trace ID (the attribution key
    carried through probes, phase stamps, and the query log)."""
    return _TRACE_PREFIX + format(next(_TRACE_SEQ), "x")


class QueryCtx:
    __slots__ = ("request", "response", "src", "protocol",
                 "client_transport", "_send", "_responded", "bytes_sent",
                 "start", "_last_stamp", "times", "log_ctx", "raw", "wire",
                 "cached_summary", "no_store", "dep_domain",
                 "want_log_detail", "trace_id", "after_done")

    def __init__(self, request: Message,
                 src: Tuple[str, int],
                 protocol: str,
                 send: Callable[[bytes], None],
                 client_transport: Optional[str] = None,
                 raw: Optional[bytes] = None) -> None:
        self.request = request
        self.raw = raw          # request wire bytes (answer-cache key)
        self.wire: Optional[bytes] = None   # encoded response after respond()
        # (answers, additional) log summaries on an answer-cache hit, so
        # the query log keeps record detail for cached responses
        self.cached_summary: Optional[Tuple[list, list]] = None
        self.src = src
        self.protocol = protocol  # 'udp' | 'tcp' | 'balancer'
        # For balancer queries: the transport the client used to reach the
        # balancer ('udp'|'tcp') — decides truncation semantics.
        self.client_transport = client_transport
        self._send = send
        # set by the recursion handoff: this response is rebuilt from
        # another DC's data, and no cache layer may keep it (the
        # balancer-socket transport propagates it as the do-not-store
        # marker, docs/balancer-protocol.md)
        self.no_store = False
        # set by the resolver at its store-lookup points: the mirrored
        # name this query's answer derives from (service node domain for
        # SRV, reverse qname for PTR) — the answer cache's per-name
        # invalidation tag
        self.dep_domain: Optional[str] = None
        # set by the server when per-query logging is on: response paths
        # that shortcut record decoding (the recursion raw splice) must
        # instead take the decoding path so log lines keep full answer
        # summaries
        self.want_log_detail = False
        self._responded = False
        # latched by the engine's _after: a query that was SHED (overload
        # admission responded for it) must not be metered again when its
        # original completion path finally runs
        self.after_done = False
        self.bytes_sent = 0
        self.start = time.monotonic()
        self._last_stamp = self.start
        self.times: Dict[str, float] = {}
        self.log_ctx: Dict[str, object] = {}
        # attribution identity: carried by probes, the query log, and
        # the per-stage stamps so one query's hops correlate across
        # layers (the reference correlates dtrace op-req-start/done by
        # the lazily-built JSON args; here the ID is explicit)
        self.trace_id = next_trace_id()

        self.response = Message(
            id=request.id, qr=True, opcode=request.opcode, aa=True,
            rd=request.rd, ra=False, questions=list(request.questions))
        opt = request.edns
        if opt is not None:
            # echo EDNS back with our payload ceiling; the OPT instance is
            # shared across queries — nothing on the serve path mutates
            # records, only the additionals *list* (which is per-query)
            self.response.additionals.append(_ECHO_OPT)

    # -- request accessors --

    def name(self) -> str:
        return self.request.questions[0].name if self.request.questions else ""

    def qtype(self) -> int:
        return (self.request.questions[0].qtype
                if self.request.questions else 0)

    def qtype_name(self) -> str:
        return Type.name(self.qtype())

    def rd(self) -> bool:
        return self.request.rd

    # -- response construction (mname addAnswer/addAuthority/addAdditional) --

    def set_error(self, rcode: int) -> None:
        self.response.rcode = rcode

    def rcode(self) -> int:
        return self.response.rcode

    def add_answer(self, record: Record) -> None:
        self.response.answers.append(record)

    def add_authority(self, record: Record) -> None:
        self.response.authorities.append(record)

    def add_additional(self, record: Record) -> None:
        self.response.additionals.append(record)

    def reset_sections(self) -> None:
        """Drop any half-built (possibly unencodable) answer set while
        KEEPING the EDNS echo: error responses (SERVFAIL after a
        handler failure, overload REFUSED) must carry the query's EDNS
        posture — a bare `additionals.clear()` silently stripped the
        OPT and broke EDNS conformance on every error path."""
        self.response.answers.clear()
        self.response.authorities.clear()
        self.response.additionals.clear()
        if self.request.edns is not None:
            self.response.additionals.append(_ECHO_OPT)

    # -- timers (lib/server.js:476-483) --

    def stamp(self, name: str) -> None:
        """Record the time (ms) since the previous stamp under ``name``
        and advance the cursor — consecutive stamps decompose the
        query's latency into non-overlapping phases (monotonic clock, so
        every recorded delta is >= 0)."""
        now = time.monotonic()
        self.times[name] = (now - self._last_stamp) * 1000.0
        self._last_stamp = now

    def record_phase(self, name: str, ms: float) -> None:
        """Record an externally measured phase duration (ms) WITHOUT
        moving the stamp cursor — for spans another layer timed itself
        (upstream RTT measured by the DNS client, event-loop wait
        measured at callback entry) that overlap the stamp timeline."""
        self.times[name] = ms

    def latency_ms(self) -> float:
        return (time.monotonic() - self.start) * 1000.0

    def last_phase(self) -> Optional[str]:
        """Name of the most recently recorded phase — the in-flight
        table's "where is this query right now" column (a query parked
        between stamps is in whatever follows its last one)."""
        try:
            return next(reversed(self.times))
        except StopIteration:
            return None

    # -- completion --

    @property
    def udp_semantics(self) -> bool:
        """True when the response travels to the client as a UDP datagram
        (directly, or via the balancer fronting a UDP client) and so must
        honor truncation.  The answer cache keys on this too — keep them
        in lockstep."""
        return (self.protocol == "udp"
                or (self.protocol == "balancer"
                    and self.client_transport != "tcp"))

    def _echo_question_case(self, wire: bytes) -> bytes:
        """dns0x20 (draft-vixie-dnsext-dns0x20): echo the requester's
        original question bytes — the encoder emits lowercase, but 0x20
        validators (including our own upstream DNS client) require the
        exact case mask back.  Declines (returns the wire unchanged) for
        any shape it can't prove safe: no raw request, multi-question,
        compressed qname, or a question that differs beyond case."""
        raw = self.raw
        if raw is None or len(raw) < 17 or raw[4:6] != b"\x00\x01" \
                or wire[4:6] != b"\x00\x01":
            return wire
        off = 12
        try:
            while True:
                ll = raw[off]
                if ll == 0:
                    off += 1
                    break
                if ll & 0xC0:
                    return wire          # compressed qname in request
                off += 1 + ll
        except IndexError:
            return wire
        q_end = off + 4
        if q_end > len(raw) or q_end > len(wire):
            return wire
        req_q = raw[12:q_end]
        if wire[12:q_end] == req_q:
            return wire                  # already identical
        if wire[12:q_end].lower() != req_q.lower():
            return wire                  # different question: leave it
        return wire[:12] + req_q + wire[q_end:]

    def respond(self) -> None:
        if self._responded:
            return
        # encode BEFORE marking responded: an encode failure must leave the
        # fallback SERVFAIL path able to answer
        if self.udp_semantics:
            wire = self.response.encode(max_size=self.request.max_udp_payload())
        else:
            wire = self.response.encode()
        wire = self._echo_question_case(wire)
        self._responded = True
        self.wire = wire
        self.bytes_sent = len(wire)
        self._send(wire)

    def respond_raw(self, wire: bytes) -> None:
        """Send a pre-encoded response (answer-cache hit), patching in
        this request's id and the requester's question case."""
        if self._responded:
            return
        wire = self.request.id.to_bytes(2, "big") + wire[2:]
        wire = self._echo_question_case(wire)
        self._responded = True
        self.wire = wire
        self.bytes_sent = len(wire)
        self._send(wire)

    @property
    def responded(self) -> bool:
        return self._responded
