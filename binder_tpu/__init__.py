"""binder_tpu — a from-scratch, capability-equivalent rebuild of
TritonDataCenter/binder (reference mounted read-only at /root/reference).

The reference is a service-discovery DNS server backed by a ZooKeeper-style
coordination store (see /root/repo/SURVEY.md for the full structural
analysis).  This package provides the rebuilt stack:

- ``binder_tpu.dns``       — DNS wire codec + asyncio server engine
                             (replaces the reference's external ``mname``
                             npm dependency, SURVEY §7.1 step 1).
- ``binder_tpu.store``     — coordination-store client interface, in-memory
                             fake store, and the watch-driven mirror cache
                             (port of ``lib/zk.js``).
- ``binder_tpu.resolver``  — query resolution engine (port of
                             ``lib/server.js``).
- ``binder_tpu.recursion`` — best-effort cross-datacenter forwarder (port of
                             ``lib/recursion.js``).
- ``binder_tpu.metrics``   — Prometheus-style metric collectors + scrape
                             server (artedi / triton-metrics analog).
- ``binder_tpu.config``    — defaults ← JSON config file ← CLI flags merge
                             (port of ``main.js`` option handling).
- ``native/``              — C++ components mirroring the reference's C:
                             load balancer (mname-balancer), instance-set
                             reconciler (smf_adjust), txnlog decoder (zklog).

Note (SURVEY §7.0): the reference contains no tensor/ML workload; this is a
control-plane system measured on DNS queries/sec and resolve latency.
"""

__version__ = "0.1.0"
