"""UFDS resolver discovery: a from-scratch LDAPv3 client.

The reference discovers other datacenters' binders with the ``ufds`` npm
package: ``listResolvers(region)`` runs the logical search ``sdc-ldap
search -b 'region=<region>, o=smartdc' objectclass=resolver``
(``lib/recursion.js:16-19,202-219``), and UFDS's own address is resolved
*through binder's ZK mirror* before connecting, since binder IS the DNS
(``lib/recursion.js:105-127``).  This module rebuilds that stack natively:

- :class:`LdapClient` — asyncio LDAPv3 (RFC 4511) over the BER codec:
  simple bind, search (equality / presence / and / or / not filters),
  unbind.  TLS optional (``ldaps://`` URLs — internal directories use
  self-signed certs, so verification is off by default, matching the
  reference deployment's ldapjs configuration; the ``recursion.ufds.ca``
  config knob opts into CA-verified TLS, which the reference cannot do).
- :class:`UfdsResolverSource` — the :class:`ResolverSource` implementation
  wired into :class:`~binder_tpu.recursion.recursion.Recursion` when the
  config carries ``recursion.ufds.url`` (sapi template
  ``sapi_manifests/binder/template:12-27``).
"""
from __future__ import annotations

import asyncio
import logging
import ssl
from typing import Dict, List, Optional, Sequence, Tuple

from binder_tpu.recursion import ber

# LDAP application tags (RFC 4511 §4.1.1), constructed form
APP_BIND_REQUEST = 0x60
APP_BIND_RESPONSE = 0x61
APP_UNBIND_REQUEST = 0x42   # primitive NULL
APP_SEARCH_REQUEST = 0x63
APP_SEARCH_ENTRY = 0x64
APP_SEARCH_DONE = 0x65

SCOPE_BASE = 0
SCOPE_ONE = 1
SCOPE_SUB = 2

RESULT_SUCCESS = 0

CONNECT_TIMEOUT = 3.0       # sapi template connectTimeout: 3000
REQUEST_TIMEOUT = 120.0     # sapi template clientTimeout: 120000


class LdapError(Exception):
    def __init__(self, msg: str, result_code: Optional[int] = None) -> None:
        super().__init__(msg)
        self.result_code = result_code


# -- filters ----------------------------------------------------------------

def parse_filter(s: str):
    """Parse an RFC 4515 filter string into an AST:
    ('eq', attr, val) | ('present', attr) | ('and'|'or', [..]) |
    ('not', node).  Substring/extensible matching is out of scope (the
    reference's one query needs none of it)."""
    s = s.strip()
    if not s.startswith("("):
        s = "(" + s + ")"
    node, pos = _parse_one(s, 0)
    if pos != len(s):
        raise LdapError(f"trailing garbage in filter: {s[pos:]!r}")
    return node


def _parse_one(s: str, pos: int):
    if s[pos] != "(":
        raise LdapError(f"expected '(' at {pos} in {s!r}")
    pos += 1
    if pos >= len(s):
        raise LdapError("unterminated filter")
    c = s[pos]
    if c in "&|":
        kids = []
        pos += 1
        while pos < len(s) and s[pos] == "(":
            kid, pos = _parse_one(s, pos)
            kids.append(kid)
        if pos >= len(s) or s[pos] != ")":
            raise LdapError("unterminated and/or filter")
        return ("and" if c == "&" else "or", kids), pos + 1
    if c == "!":
        kid, pos = _parse_one(s, pos + 1)
        if pos >= len(s) or s[pos] != ")":
            raise LdapError("unterminated not filter")
        return ("not", kid), pos + 1
    end = s.find(")", pos)
    if end < 0:
        raise LdapError("unterminated comparison")
    body = s[pos:end]
    if "=" not in body:
        raise LdapError(f"no '=' in filter component {body!r}")
    attr, _, val = body.partition("=")
    attr = attr.strip()
    if not attr:
        raise LdapError("empty attribute in filter")
    if val == "*":
        return ("present", attr), end + 1
    if "*" in val:
        raise LdapError("substring filters not supported")
    return ("eq", attr, val), end + 1


def encode_filter(node) -> bytes:
    kind = node[0]
    if kind == "eq":
        return ber.encode_seq(
            [ber.encode_str(node[1]), ber.encode_str(node[2])], tag=0xA3)
    if kind == "present":
        return ber.encode_str(node[1], tag=0x87)
    if kind == "and":
        return ber.encode_seq([encode_filter(k) for k in node[1]], tag=0xA0)
    if kind == "or":
        return ber.encode_seq([encode_filter(k) for k in node[1]], tag=0xA1)
    if kind == "not":
        return ber.encode_seq([encode_filter(node[1])], tag=0xA2)
    raise LdapError(f"unknown filter node {kind!r}")


def eval_filter(node, attrs: Dict[str, List[str]]) -> bool:
    """Evaluate a filter AST against a case-folded attribute dict
    (used by the in-process test directory)."""
    kind = node[0]
    if kind == "eq":
        vals = attrs.get(node[1].lower(), [])
        return any(v.lower() == node[2].lower() for v in vals)
    if kind == "present":
        return node[1].lower() in attrs
    if kind == "and":
        return all(eval_filter(k, attrs) for k in node[1])
    if kind == "or":
        return any(eval_filter(k, attrs) for k in node[1])
    if kind == "not":
        return not eval_filter(node[1], attrs)
    raise LdapError(f"unknown filter node {kind!r}")


def normalize_dn(dn: str) -> str:
    return ",".join(part.strip().lower() for part in dn.split(","))


# -- client -----------------------------------------------------------------

class LdapClient:
    """Asyncio LDAPv3 client: connect / simple bind / search / unbind."""

    def __init__(self, host: str, port: int = 389, *, tls: bool = False,
                 tls_context: Optional[ssl.SSLContext] = None,
                 server_name: Optional[str] = None,
                 connect_timeout: float = CONNECT_TIMEOUT,
                 request_timeout: float = REQUEST_TIMEOUT,
                 log: Optional[logging.Logger] = None) -> None:
        self.host = host
        self.port = port
        self.tls = tls
        # a caller-built verifying context (None keeps the
        # reference-compatible trust-anything default); server_name is
        # the certificate identity to check when it differs from the
        # dialed host (UFDS is dialed by ZK-resolved IP, verified
        # against its DNS name)
        self.tls_context = tls_context
        self.server_name = server_name
        self.connect_timeout = connect_timeout
        self.request_timeout = request_timeout
        self.log = log or logging.getLogger("binder.ldap")
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._msgid = 0
        self._buf = b""

    @property
    def connected(self) -> bool:
        return self._writer is not None and not self._writer.is_closing()

    async def connect(self) -> None:
        sslctx = None
        kwargs = {}
        if self.tls:
            if self.tls_context is not None:
                sslctx = self.tls_context
            else:
                # internal DC directory, self-signed certs (reference
                # ldapjs config does the equivalent); opt into
                # verification via UfdsResolverSource's `ca` knob
                sslctx = ssl.create_default_context()
                sslctx.check_hostname = False
                sslctx.verify_mode = ssl.CERT_NONE
            if self.server_name:
                kwargs["server_hostname"] = self.server_name
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port, ssl=sslctx,
                                    **kwargs),
            self.connect_timeout)
        self._buf = b""

    async def close(self) -> None:
        if self._writer is not None:
            try:
                self._writer.write(
                    ber.encode_seq([ber.encode_int(self._next_id()),
                                    ber.tlv(APP_UNBIND_REQUEST, b"")]))
                await self._writer.drain()
            except (ConnectionError, OSError):
                pass
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError, asyncio.CancelledError):
                pass
        self._reader = self._writer = None

    def _next_id(self) -> int:
        self._msgid += 1
        return self._msgid

    async def _send(self, msgid: int, op: bytes) -> None:
        if not self.connected:
            raise LdapError("not connected")
        self._writer.write(ber.encode_seq([ber.encode_int(msgid), op]))
        await self._writer.drain()

    async def _read_message(self) -> Tuple[int, int, bytes]:
        """Read one LDAPMessage → (msgid, op_tag, op_content)."""
        while True:
            total = ber.frame_length(self._buf)
            if total:
                frame, self._buf = self._buf[:total], self._buf[total:]
                tag, content, _ = ber.decode_tlv(frame)
                if tag != ber.SEQUENCE:
                    raise LdapError(f"bad LDAPMessage tag {tag:#x}")
                parts = ber.decode_all(content)
                if len(parts) < 2 or parts[0][0] != ber.INTEGER:
                    raise LdapError("malformed LDAPMessage")
                return (ber.decode_int(parts[0][1]),
                        parts[1][0], parts[1][1])
            chunk = await asyncio.wait_for(self._reader.read(65536),
                                           self.request_timeout)
            if not chunk:
                raise LdapError("connection closed by server")
            self._buf += chunk

    @staticmethod
    def _parse_result(content: bytes) -> Tuple[int, str]:
        parts = ber.decode_all(content)
        if len(parts) < 3:
            raise LdapError("malformed LDAPResult")
        code = ber.decode_int(parts[0][1])
        diag = parts[2][1].decode("utf-8", "replace")
        return code, diag

    async def bind(self, dn: str, password: str) -> None:
        msgid = self._next_id()
        op = ber.encode_seq([
            ber.encode_int(3),                     # version
            ber.encode_str(dn),
            ber.encode_str(password, tag=0x80),    # simple auth [0]
        ], tag=APP_BIND_REQUEST)
        await self._send(msgid, op)
        rid, tag, content = await self._read_message()
        if rid != msgid or tag != APP_BIND_RESPONSE:
            raise LdapError(f"unexpected bind reply (id {rid}, tag {tag:#x})")
        code, diag = self._parse_result(content)
        if code != RESULT_SUCCESS:
            raise LdapError(f"bind failed: {diag or code}", code)

    async def search(self, base: str, filter_str: str, *,
                     scope: int = SCOPE_SUB,
                     attributes: Sequence[str] = ()) \
            -> List[Tuple[str, Dict[str, List[str]]]]:
        """Return [(dn, {attr: [values]}), ...]; attr keys lowercased."""
        msgid = self._next_id()
        op = ber.encode_seq([
            ber.encode_str(base),
            ber.encode_int(scope, tag=ber.ENUMERATED),
            ber.encode_int(0, tag=ber.ENUMERATED),   # derefAliases: never
            ber.encode_int(0),                       # sizeLimit
            ber.encode_int(0),                       # timeLimit
            ber.encode_bool(False),                  # typesOnly
            encode_filter(parse_filter(filter_str)),
            ber.encode_seq([ber.encode_str(a) for a in attributes]),
        ], tag=APP_SEARCH_REQUEST)
        await self._send(msgid, op)

        entries: List[Tuple[str, Dict[str, List[str]]]] = []
        while True:
            rid, tag, content = await self._read_message()
            if rid != msgid:
                continue   # stale reply from an abandoned operation
            if tag == APP_SEARCH_ENTRY:
                entries.append(self._parse_entry(content))
            elif tag == APP_SEARCH_DONE:
                code, diag = self._parse_result(content)
                if code != RESULT_SUCCESS:
                    raise LdapError(f"search failed: {diag or code}", code)
                return entries
            else:
                raise LdapError(f"unexpected search reply tag {tag:#x}")

    @staticmethod
    def _parse_entry(content: bytes) -> Tuple[str, Dict[str, List[str]]]:
        parts = ber.decode_all(content)
        if len(parts) != 2:
            raise LdapError("malformed SearchResultEntry")
        dn = parts[0][1].decode("utf-8", "replace")
        attrs: Dict[str, List[str]] = {}
        for tag, body in ber.decode_all(parts[1][1]):
            kv = ber.decode_all(body)
            if len(kv) != 2:
                continue
            name = kv[0][1].decode("utf-8", "replace").lower()
            vals = [v.decode("utf-8", "replace")
                    for _, v in ber.decode_all(kv[1][1])]
            attrs[name] = vals
        return dn, attrs


# -- the ResolverSource implementation --------------------------------------

def parse_ldap_url(url: str) -> Tuple[str, Optional[str], Optional[int]]:
    """'ldaps://host[:port]' → (scheme, host, port); bracketed IPv6
    literals ('ldaps://[fd00::5]:636') keep their colons."""
    scheme, sep, rest = url.partition("://")
    if not sep:
        scheme, rest = "ldaps", url
    if rest.startswith("["):
        end = rest.find("]")
        if end < 0:
            raise LdapError(f"unterminated IPv6 literal in {url!r}")
        host, rest = rest[1:end], rest[end + 1:]
        port = rest[1:] if rest.startswith(":") else ""
    else:
        host, _, port = rest.partition(":")
    try:
        return scheme.lower(), host or None, int(port) if port else None
    except ValueError:
        raise LdapError(f"bad port in ldap url {url!r}")


class UfdsResolverSource:
    """Resolver discovery against a UFDS LDAP directory.

    ``init`` resolves the directory's DNS name through binder's own ZK
    mirror — binder *is* the DNS, so it can't use a stub resolver
    (``lib/recursion.js:105-127``) — then binds.  ``list_resolvers``
    searches ``region=<region>, o=smartdc`` for ``objectclass=resolver``
    entries carrying ``datacenter`` and ``ip`` attributes
    (``lib/recursion.js:16-19`` and the ufds client's listResolvers)."""

    def __init__(self, config: dict,
                 log: Optional[logging.Logger] = None) -> None:
        self.url = config.get("url", "")
        self.bind_dn = config.get("bindDN", "")
        self.bind_password = config.get("bindPassword", "")
        self.connect_timeout = config.get("connectTimeout", 3000) / 1000.0
        self.request_timeout = config.get("clientTimeout", 120000) / 1000.0
        # CA verification opt-in (beats the reference: lib/recursion.js
        # 129-148 trusts any certificate).  `ca` is a PEM bundle path;
        # when set, the chain is verified against it and the certificate
        # identity is checked against `tlsServerName` if given, else the
        # url's DNS name (the dial target itself is usually a
        # ZK-resolved IP).  Unset keeps the reference-compatible
        # trust-anything default.  Built once here so a bad CA path is
        # an immediate config error, not a silently retried warning.
        self.ca = config.get("ca")
        self.tls_server_name = config.get("tlsServerName")
        if self.tls_server_name and not self.ca:
            # identity pinning without a trust root would silently fall
            # back to the trust-anything context — refuse instead
            raise LdapError("ufds.tlsServerName requires ufds.ca")
        self._tls_context: Optional[ssl.SSLContext] = None
        self._server_name: Optional[str] = None
        if self.ca:
            try:
                self._tls_context = ssl.create_default_context(
                    cafile=self.ca)
            except (OSError, ssl.SSLError) as e:
                raise LdapError(f"cannot load ufds.ca {self.ca!r}: {e}")
            url_host = None
            if self.url:
                try:
                    _, h, _ = parse_ldap_url(self.url)
                except LdapError:
                    h = None   # init() re-parses and raises with context
                if h and not _is_address(h):
                    url_host = h
            self._server_name = self.tls_server_name or url_host
            if self._server_name is None:
                # nothing to check the certificate identity against
                # (address-literal url, no pinned name): chain
                # verification only
                self._tls_context.check_hostname = False
        self.log = log or logging.getLogger("binder.ufds")
        self.client: Optional[LdapClient] = None
        self._addr: Optional[Tuple[str, int, bool]] = None

    async def init(self, zk_cache) -> None:
        scheme, host, port = parse_ldap_url(self.url)
        tls = scheme == "ldaps"
        if port is None:
            port = 636 if tls else 389
        addr = host
        if addr is None:
            raise LdapError(f"no host in ufds url {self.url!r}")
        # resolve through the ZK mirror unless the config already names an
        # address literal
        if not _is_address(addr):
            if not zk_cache.is_ready():
                raise LdapError("ZK is not yet available")
            node = zk_cache.lookup(addr)
            data = getattr(node, "data", None)
            kids = getattr(node, "children", None) or []
            if (node is None or not data or data.get("type") != "service"
                    or not kids):
                raise LdapError("not yet able to resolve ufds")
            kid = kids[0]
            addr = kid.data[kid.data["type"]]["address"]
        self._addr = (addr, port, tls)
        await self._connect()

    async def _connect(self) -> None:
        assert self._addr is not None
        if self.client is not None:
            # init retries / reconnects must not leak the previous socket
            await self.client.close()
            self.client = None
        host, port, tls = self._addr
        client = LdapClient(host, port, tls=tls,
                            tls_context=self._tls_context,
                            server_name=self._server_name,
                            connect_timeout=self.connect_timeout,
                            request_timeout=self.request_timeout,
                            log=self.log)
        await client.connect()
        try:
            await client.bind(self.bind_dn, self.bind_password)
        except BaseException:
            await client.close()
            raise
        self.client = client
        self.log.info("UFDS connected (%s:%d%s)", host, port,
                      " tls" if tls else "")

    async def list_resolvers(self, region_name: str) -> List[Dict[str, str]]:
        if self.client is None or not self.client.connected:
            if self._addr is None:
                raise LdapError("UFDS is not available yet.")
            await self._connect()
        base = f"region={region_name}, o=smartdc"
        try:
            entries = await self.client.search(
                base, "(objectclass=resolver)",
                attributes=("datacenter", "ip"))
        except (LdapError, ber.BerError, ConnectionError, OSError,
                asyncio.TimeoutError):
            # drop the connection so the next refresh reconnects — a
            # malformed frame also poisons the stream buffer, so the
            # connection is unusable either way
            await self.close()
            raise
        out = []
        for dn, attrs in entries:
            dc = (attrs.get("datacenter") or [""])[0]
            ip = (attrs.get("ip") or [""])[0]
            if dc and ip:
                out.append({"datacenter": dc, "ip": ip})
            else:
                self.log.warning("UFDS resolver entry %s missing "
                                 "datacenter/ip, skipping", dn)
        return out

    async def close(self) -> None:
        if self.client is not None:
            await self.client.close()
            self.client = None


def _is_address(host: str) -> bool:
    import ipaddress
    try:
        ipaddress.ip_address(host)
        return True
    except ValueError:
        return False
