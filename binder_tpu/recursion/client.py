"""Async DNS client for upstream queries (mname-client equivalent).

The reference forwards cross-DC queries with mname-client's DnsClient
(``lib/recursion.js:64-79,253-279``): bounded concurrency across the
resolver list, 3s timeout, first NOERROR response wins, and for PTR
fan-out an error threshold equal to the whole resolver list.  This module
reimplements that surface on asyncio with our own wire codec.

Resolvers may be given as ``"ip"`` (port 53) or ``"ip:port"`` (tests,
non-standard deployments).
"""
from __future__ import annotations

import asyncio
import logging
import random
from typing import List, Optional, Sequence, Tuple

from binder_tpu.dns.wire import Message, Rcode, Record, make_query
from binder_tpu.utils.endpoints import parse_endpoint

DEFAULT_TIMEOUT = 3.0  # lib/recursion.js:257


class UpstreamError(Exception):
    """No upstream produced a usable answer."""


def _parse_resolver(r: str) -> Tuple[str, int]:
    return parse_endpoint(r, 53)


class _PortProto(asyncio.DatagramProtocol):
    """Shared connected-UDP endpoint for one upstream, id-multiplexed:
    qid -> (future, expected question bytes).

    Sharing a socket fixes the local port for the client's lifetime,
    which on its own would cut blind-spoofing entropy to the 16-bit id
    (the connected-socket peer filter does not stop packets forged with
    the resolver's source address).  The lost entropy is restored with
    dns0x20 (draft-vixie-dnsext-dns0x20): every query's qname gets a
    random case mask, and a response only counts if it echoes the
    question section byte-for-byte — anything else is dropped silently
    and the real answer keeps being awaited."""

    def __init__(self) -> None:
        self.pending: dict = {}
        self.transport = None
        self.case_mismatch_drops = 0
        self.log = logging.getLogger("binder.dnsclient")

    def connection_made(self, transport) -> None:
        self.transport = transport

    def datagram_received(self, data, addr) -> None:
        if len(data) < 12:
            return
        entry = self.pending.get((data[0] << 8) | data[1])
        if entry is None:
            return                      # late/duplicate response
        fut, expect_q = entry
        if fut.done():
            return
        # verbatim question echo (id + 0x20 case mask) or it's not ours
        if data[12:12 + len(expect_q)] != expect_q:
            # either a spoof attempt or an 0x20-incompatible upstream
            # (one that case-normalizes the echoed question): surface it,
            # rate-limited, or every lookup is an undiagnosable timeout
            self.case_mismatch_drops += 1
            n = self.case_mismatch_drops
            if n & (n - 1) == 0:        # 1, 2, 4, 8, ...
                self.log.warning(
                    "dropping upstream response with mismatched question "
                    "echo (dns0x20); %d dropped on this socket so far "
                    "(0x20-incompatible upstream, or spoofed traffic)", n)
            return
        del self.pending[(data[0] << 8) | data[1]]
        try:
            msg = Message.decode(data)
        except Exception as e:  # noqa: BLE001 — malformed upstream bytes
            fut.set_exception(WireTimeout(f"bad upstream response: {e}"))
            return
        fut.set_result(msg)

    def _fail_all(self, exc) -> None:
        for fut, _q in self.pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self.pending.clear()

    def error_received(self, exc) -> None:
        # ICMP errors carry no query attribution on a connected socket;
        # everything in flight to this upstream is dead
        self._fail_all(exc)

    def connection_lost(self, exc) -> None:
        self._fail_all(exc or ConnectionError("upstream socket closed"))


def _close_transport(proto: "_PortProto") -> None:
    """Close a pooled transport; if its event loop is already gone,
    release the underlying socket fd directly."""
    if proto.transport is None:
        return
    try:
        proto.transport.close()
    except Exception:  # noqa: BLE001 — owning loop closed
        sock = proto.transport.get_extra_info("socket")
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


class DnsClient:
    """Queries a set of upstream resolvers with bounded concurrency.

    One connected UDP socket is kept per upstream and shared by every
    in-flight query (id-multiplexed) — per-query socket creation would
    dominate the forwarding path's cost and churn ephemeral ports."""

    def __init__(self, concurrency: int = 2,
                 timeout: float = DEFAULT_TIMEOUT,
                 log: Optional[logging.Logger] = None) -> None:
        self.concurrency = concurrency
        self.timeout = timeout
        self.log = log or logging.getLogger("binder.dnsclient")
        # (host, port) -> (loop, _PortProto); recreated if the transport
        # died or the entry belongs to a previous event loop (tests run
        # several loops in one process)
        self._ports: dict = {}

    async def _get_port(self, host: str, port: int) -> _PortProto:
        loop = asyncio.get_running_loop()
        entry = self._ports.get((host, port))
        if entry is not None:
            e_loop, proto = entry
            if (e_loop is loop and proto.transport is not None
                    and not proto.transport.is_closing()):
                return proto
            _close_transport(proto)     # dead or from a previous loop
            self._ports.pop((host, port), None)
        transport, proto = await loop.create_datagram_endpoint(
            _PortProto, remote_addr=(host, port))
        # a concurrent first query may have created the port while we
        # awaited; keep the stored one and release ours, or every
        # 100-way PTR fan-out would leak sockets
        entry = self._ports.get((host, port))
        if entry is not None and entry[0] is loop \
                and entry[1].transport is not None \
                and not entry[1].transport.is_closing():
            transport.close()
            return entry[1]
        self._ports[(host, port)] = (loop, proto)
        return proto

    def close(self) -> None:
        for (_e_loop, proto) in self._ports.values():
            _close_transport(proto)
        self._ports.clear()

    def prune(self, keep: "set") -> None:
        """Close pooled sockets for upstreams no longer in the resolver
        set (long-lived processes see resolver churn; without pruning,
        one fd per address ever seen accumulates).  In-flight sockets
        are kept — the next prune after they drain gets them."""
        for key in list(self._ports):
            _e_loop, proto = self._ports[key]
            if key not in keep and not proto.pending:
                _close_transport(proto)
                del self._ports[key]

    async def lookup(self, name: str, qtype: int,
                     resolvers: Sequence[str],
                     error_threshold: Optional[int] = None
                     ) -> List[Record]:
        """Return the answers from the first NOERROR upstream response.

        Tries *resolvers* with at most ``concurrency`` queries in flight;
        gives up once ``error_threshold`` upstreams have failed (default:
        all of them, matching mname-client's behavior of walking the whole
        list).
        """
        if not resolvers:
            raise UpstreamError("no upstream resolvers")
        threshold = (len(resolvers) if error_threshold is None
                     else error_threshold)

        sem = asyncio.Semaphore(self.concurrency)
        errors: List[str] = []
        done_count = [0]
        winner: asyncio.Future = asyncio.get_running_loop().create_future()

        async def one(resolver: str) -> None:
            try:
                async with sem:
                    if winner.done():
                        return
                    try:
                        msg = await self._query_one(name, qtype, resolver)
                    except Exception as e:  # noqa: BLE001 — any failure
                        # counts against the threshold; an uncounted error
                        # (e.g. a malformed resolver string) would hang
                        # the lookup forever
                        errors.append(f"{resolver}: {e}")
                    else:
                        if msg.rcode == Rcode.NOERROR and msg.tc:
                            # truncated: retry the same resolver over
                            # TCP before counting it as a failure
                            # (mname-client capability the reference
                            # relies on for large PTR/SRV answer sets,
                            # lib/recursion.js:253-279)
                            try:
                                msg = await self._query_one_tcp(
                                    name, qtype, resolver)
                            except Exception as e:  # noqa: BLE001
                                errors.append(
                                    f"{resolver}: tcp retry: {e}")
                                msg = None
                        if (msg is not None
                                and msg.rcode == Rcode.NOERROR
                                and not msg.tc):
                            if not winner.done():
                                winner.set_result(msg.answers)
                            return
                        if msg is not None:
                            errors.append(
                                f"{resolver}: "
                                + ("truncated" if msg.tc
                                   else f"rcode {Rcode.name(msg.rcode)}"))
                    if len(errors) >= threshold and not winner.done():
                        winner.set_exception(UpstreamError(
                            "; ".join(errors[-4:])))
            finally:
                done_count[0] += 1
                if done_count[0] == len(resolvers) and not winner.done():
                    winner.set_exception(UpstreamError(
                        "; ".join(errors[-4:]) or "all upstreams failed"))

        tasks = [asyncio.ensure_future(one(r)) for r in resolvers]
        try:
            return await winner
        finally:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

    async def _query_one(self, name: str, qtype: int,
                         resolver: str) -> Message:
        host, port = _parse_resolver(resolver)
        proto = await self._get_port(host, port)
        loop = asyncio.get_running_loop()
        # qid must be unique among this upstream's in-flight queries
        qid = random.randrange(0, 65536)
        while qid in proto.pending:
            qid = random.randrange(0, 65536)
        # Forwarded queries must not re-recurse: clear RD
        # (lib/recursion.js:259-261)
        query = make_query(name, qtype, qid=qid, rd=False)
        wire = bytearray(query.encode())
        # dns0x20: random case mask over the qname's alpha bytes (the
        # encoder emits lowercase; a fresh query's qname sits at offset
        # 12, uncompressed); the response must echo these exact bytes
        off = 12
        while wire[off] != 0:
            ll = wire[off]
            for i in range(off + 1, off + 1 + ll):
                if 0x61 <= wire[i] <= 0x7A and random.getrandbits(1):
                    wire[i] -= 0x20
            off += 1 + ll
        expect_q = bytes(wire[12:off + 5])   # qname + terminator + type/class
        fut: asyncio.Future = loop.create_future()
        proto.pending[qid] = (fut, expect_q)
        try:
            proto.transport.sendto(bytes(wire))
            return await asyncio.wait_for(fut, self.timeout)
        finally:
            # pop only our own entry: after this qid was released (answer
            # delivered / socket failed), another query may have re-used
            # it before this finally ran
            cur = proto.pending.get(qid)
            if cur is not None and cur[0] is fut:
                del proto.pending[qid]

    async def _query_one_tcp(self, name: str, qtype: int,
                             resolver: str) -> Message:
        """RFC 1035 §4.2.2 framed query — the truncation fallback."""
        host, port = _parse_resolver(resolver)
        qid = random.randrange(0, 65536)
        query = make_query(name, qtype, qid=qid, rd=False)
        wire = query.encode()

        async def go() -> Message:
            reader, writer = await asyncio.open_connection(host, port)
            try:
                writer.write(len(wire).to_bytes(2, "big") + wire)
                await writer.drain()
                hdr = await reader.readexactly(2)
                n = int.from_bytes(hdr, "big")
                msg = Message.decode(await reader.readexactly(n))
                if msg.id != qid:
                    raise WireTimeout("upstream TCP answer id mismatch")
                return msg
            finally:
                writer.close()
                try:
                    await writer.wait_closed()
                except (ConnectionResetError, BrokenPipeError, OSError):
                    pass

        return await asyncio.wait_for(go(), self.timeout)


class WireTimeout(Exception):
    pass
