"""Async DNS client for upstream queries (mname-client equivalent).

The reference forwards cross-DC queries with mname-client's DnsClient
(``lib/recursion.js:64-79,253-279``): bounded concurrency across the
resolver list, 3s timeout, first NOERROR response wins, and for PTR
fan-out an error threshold equal to the whole resolver list.  This module
reimplements that surface on asyncio with our own wire codec.

Resolvers may be given as ``"ip"`` (port 53) or ``"ip:port"`` (tests,
non-standard deployments).
"""
from __future__ import annotations

import asyncio
import logging
import random
from typing import List, Optional, Sequence, Tuple

from binder_tpu.dns.wire import Message, Rcode, Record, make_query
from binder_tpu.utils.endpoints import parse_endpoint

DEFAULT_TIMEOUT = 3.0  # lib/recursion.js:257


class UpstreamError(Exception):
    """No upstream produced a usable answer."""


def _parse_resolver(r: str) -> Tuple[str, int]:
    return parse_endpoint(r, 53)


class DnsClient:
    """Queries a set of upstream resolvers with bounded concurrency."""

    def __init__(self, concurrency: int = 2,
                 timeout: float = DEFAULT_TIMEOUT,
                 log: Optional[logging.Logger] = None) -> None:
        self.concurrency = concurrency
        self.timeout = timeout
        self.log = log or logging.getLogger("binder.dnsclient")

    async def lookup(self, name: str, qtype: int,
                     resolvers: Sequence[str],
                     error_threshold: Optional[int] = None
                     ) -> List[Record]:
        """Return the answers from the first NOERROR upstream response.

        Tries *resolvers* with at most ``concurrency`` queries in flight;
        gives up once ``error_threshold`` upstreams have failed (default:
        all of them, matching mname-client's behavior of walking the whole
        list).
        """
        if not resolvers:
            raise UpstreamError("no upstream resolvers")
        threshold = (len(resolvers) if error_threshold is None
                     else error_threshold)

        sem = asyncio.Semaphore(self.concurrency)
        errors: List[str] = []
        done_count = [0]
        winner: asyncio.Future = asyncio.get_running_loop().create_future()

        async def one(resolver: str) -> None:
            try:
                async with sem:
                    if winner.done():
                        return
                    try:
                        msg = await self._query_one(name, qtype, resolver)
                    except Exception as e:  # noqa: BLE001 — any failure
                        # counts against the threshold; an uncounted error
                        # (e.g. a malformed resolver string) would hang
                        # the lookup forever
                        errors.append(f"{resolver}: {e}")
                    else:
                        if msg.rcode == Rcode.NOERROR and msg.tc:
                            # truncated: retry the same resolver over
                            # TCP before counting it as a failure
                            # (mname-client capability the reference
                            # relies on for large PTR/SRV answer sets,
                            # lib/recursion.js:253-279)
                            try:
                                msg = await self._query_one_tcp(
                                    name, qtype, resolver)
                            except Exception as e:  # noqa: BLE001
                                errors.append(
                                    f"{resolver}: tcp retry: {e}")
                                msg = None
                        if (msg is not None
                                and msg.rcode == Rcode.NOERROR
                                and not msg.tc):
                            if not winner.done():
                                winner.set_result(msg.answers)
                            return
                        if msg is not None:
                            errors.append(
                                f"{resolver}: "
                                + ("truncated" if msg.tc
                                   else f"rcode {Rcode.name(msg.rcode)}"))
                    if len(errors) >= threshold and not winner.done():
                        winner.set_exception(UpstreamError(
                            "; ".join(errors[-4:])))
            finally:
                done_count[0] += 1
                if done_count[0] == len(resolvers) and not winner.done():
                    winner.set_exception(UpstreamError(
                        "; ".join(errors[-4:]) or "all upstreams failed"))

        tasks = [asyncio.ensure_future(one(r)) for r in resolvers]
        try:
            return await winner
        finally:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

    async def _query_one(self, name: str, qtype: int,
                         resolver: str) -> Message:
        host, port = _parse_resolver(resolver)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        qid = random.randrange(0, 65536)
        # Forwarded queries must not re-recurse: clear RD
        # (lib/recursion.js:259-261)
        query = make_query(name, qtype, qid=qid, rd=False)

        class Proto(asyncio.DatagramProtocol):
            def connection_made(self, transport):
                self.transport = transport
                transport.sendto(query.encode())

            def datagram_received(self, data, addr):
                try:
                    msg = Message.decode(data)
                except Exception as e:  # noqa: BLE001
                    if not fut.done():
                        fut.set_exception(
                            WireTimeout(f"bad upstream response: {e}"))
                    return
                if msg.id == qid and not fut.done():
                    fut.set_result(msg)

            def error_received(self, exc):
                if not fut.done():
                    fut.set_exception(exc)

        transport, _ = await loop.create_datagram_endpoint(
            Proto, remote_addr=(host, port))
        try:
            return await asyncio.wait_for(fut, self.timeout)
        finally:
            transport.close()

    async def _query_one_tcp(self, name: str, qtype: int,
                             resolver: str) -> Message:
        """RFC 1035 §4.2.2 framed query — the truncation fallback."""
        host, port = _parse_resolver(resolver)
        qid = random.randrange(0, 65536)
        query = make_query(name, qtype, qid=qid, rd=False)
        wire = query.encode()

        async def go() -> Message:
            reader, writer = await asyncio.open_connection(host, port)
            try:
                writer.write(len(wire).to_bytes(2, "big") + wire)
                await writer.drain()
                hdr = await reader.readexactly(2)
                n = int.from_bytes(hdr, "big")
                msg = Message.decode(await reader.readexactly(n))
                if msg.id != qid:
                    raise WireTimeout("upstream TCP answer id mismatch")
                return msg
            finally:
                writer.close()
                try:
                    await writer.wait_closed()
                except (ConnectionResetError, BrokenPipeError, OSError):
                    pass

        return await asyncio.wait_for(go(), self.timeout)


class WireTimeout(Exception):
    pass
