"""Async DNS client for upstream queries (mname-client equivalent).

The reference forwards cross-DC queries with mname-client's DnsClient
(``lib/recursion.js:64-79,253-279``): bounded concurrency across the
resolver list, 3s timeout, first NOERROR response wins, and for PTR
fan-out an error threshold equal to the whole resolver list.  This module
reimplements that surface on asyncio with our own wire codec.

Resolvers may be given as ``"ip"`` (port 53) or ``"ip:port"`` (tests,
non-standard deployments).
"""
from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import List, Optional, Sequence, Tuple

from binder_tpu.dns.wire import (Message, Rcode, Record,
                                 make_query, wire_walks)
from binder_tpu.utils.endpoints import parse_endpoint

DEFAULT_TIMEOUT = 3.0  # lib/recursion.js:257


class UpstreamError(Exception):
    """No upstream produced a usable answer.

    ``got_response`` distinguishes *how* it failed: True means at least
    one upstream returned a DNS response (an error rcode, truncation, a
    malformed body — the peer is alive and said no); False means pure
    transport failure (timeouts, socket death, all breakers open — the
    peer may be dark).  The federation layer serves stale only on the
    latter: a live peer's negative answer must stay a negative answer.
    """

    def __init__(self, msg: str = "", got_response: bool = False) -> None:
        super().__init__(msg)
        self.got_response = got_response


def _parse_resolver(r: str) -> Tuple[str, int]:
    return parse_endpoint(r, 53)


class _PortProto(asyncio.DatagramProtocol):
    """Shared connected-UDP endpoint for one upstream, id-multiplexed:
    qid -> (future, expected question bytes).

    Sharing a socket fixes the local port for the client's lifetime,
    which on its own would cut blind-spoofing entropy to the 16-bit id
    (the connected-socket peer filter does not stop packets forged with
    the resolver's source address).  The lost entropy is restored with
    dns0x20 (draft-vixie-dnsext-dns0x20): every query's qname gets a
    random case mask, and a response only counts if it echoes the
    question section byte-for-byte — anything else is dropped silently
    and the real answer keeps being awaited."""

    def __init__(self) -> None:
        self.pending: dict = {}         # qid -> (fut, expect_q, deadline)
        self.transport = None
        self.case_mismatch_drops = 0
        self.log = logging.getLogger("binder.dnsclient")
        # Timeout handling is a periodic deadline sweep over `pending`
        # instead of one wait_for timer per query: the forwarding hot
        # path creates/cancels zero timer handles, and a sweep over a
        # small dict every ~quarter second is noise.
        self._sweep_handle = None

    def connection_made(self, transport) -> None:
        self.transport = transport

    def _arm_sweep(self, loop, interval: float) -> None:
        if self._sweep_handle is None:
            self._sweep_handle = loop.call_later(interval, self._sweep,
                                                 loop, interval)

    def _sweep(self, loop, interval: float) -> None:
        self._sweep_handle = None
        if self.transport is None or self.transport.is_closing():
            return
        now = loop.time()
        expired = [qid for qid, (_f, _q, dl) in self.pending.items()
                   if dl <= now]
        for qid in expired:
            fut, _q, _dl = self.pending.pop(qid)
            if not fut.done():
                fut.set_exception(WireTimeout("upstream timeout"))
        if self.pending:
            self._arm_sweep(loop, interval)

    def datagram_received(self, data, addr) -> None:
        if len(data) < 12:
            return
        entry = self.pending.get((data[0] << 8) | data[1])
        if entry is None:
            return                      # late/duplicate response
        fut, expect_q, _deadline = entry
        if fut.done():
            return
        # verbatim question echo (id + 0x20 case mask) or it's not ours
        if data[12:12 + len(expect_q)] != expect_q:
            # either a spoof attempt or an 0x20-incompatible upstream
            # (one that case-normalizes the echoed question): surface it,
            # rate-limited, or every lookup is an undiagnosable timeout
            self.case_mismatch_drops += 1
            n = self.case_mismatch_drops
            if n & (n - 1) == 0:        # 1, 2, 4, 8, ...
                self.log.warning(
                    "dropping upstream response with mismatched question "
                    "echo (dns0x20); %d dropped on this socket so far "
                    "(0x20-incompatible upstream, or spoofed traffic)", n)
            return
        del self.pending[(data[0] << 8) | data[1]]
        # validated raw bytes (id + verbatim question echo); decoding is
        # deferred to the consumer — the splice path (recursion.py)
        # forwards the wire without ever building record objects.
        # Arrival stamp rides the future: the gap between this moment
        # and the done-callback running is event-loop wait, the half of
        # recursive latency the attribution layer must separate from
        # the upstream RTT (recursion._complete reads it back).
        fut.binder_recv_t = time.monotonic()
        fut.set_result(bytes(data))

    def _fail_all(self, exc) -> None:
        for fut, _q, _dl in self.pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self.pending.clear()
        if self._sweep_handle is not None:
            self._sweep_handle.cancel()
            self._sweep_handle = None

    def error_received(self, exc) -> None:
        # ICMP errors carry no query attribution on a connected socket;
        # everything in flight to this upstream is dead
        self._fail_all(exc)

    def connection_lost(self, exc) -> None:
        self._fail_all(exc or ConnectionError("upstream socket closed"))


def _close_transport(proto: "_PortProto") -> None:
    """Close a pooled transport; if its event loop is already gone,
    release the underlying socket fd directly."""
    if proto.transport is None:
        return
    try:
        proto.transport.close()
    except Exception:  # noqa: BLE001 — owning loop closed
        sock = proto.transport.get_extra_info("socket")
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


class DnsClient:
    """Queries a set of upstream resolvers with bounded concurrency.

    One connected UDP socket is kept per upstream and shared by every
    in-flight query (id-multiplexed) — per-query socket creation would
    dominate the forwarding path's cost and churn ephemeral ports."""

    #: encoded-query templates kept per (name, qtype) — forwarders
    #: re-ask the same names continuously, and make_query+encode per
    #: forward costs more than the rest of the client path combined
    _TMPL_MAX = 4096

    def __init__(self, concurrency: int = 2,
                 timeout: float = DEFAULT_TIMEOUT,
                 log: Optional[logging.Logger] = None,
                 breakers=None) -> None:
        self.concurrency = concurrency
        self.timeout = timeout
        self.log = log or logging.getLogger("binder.dnsclient")
        # per-peer circuit breakers + latency stats
        # (binder_tpu/policy/breaker.py), shared with the owning
        # Recursion: open peers are skipped before any packet is sent,
        # and p95 latency drives the hedged-request stagger in
        # lookup_raw.  None = classic behavior (bare-client tests).
        self.breakers = breakers
        # (host, port) -> (loop, _PortProto); recreated if the transport
        # died or the entry belongs to a previous event loop (tests run
        # several loops in one process)
        self._ports: dict = {}
        self._tmpl: dict = {}
        self._resolver_keys: dict = {}   # "ip:port" -> (host, port)
        # single-flight: concurrent identical lookups collapse onto one
        # upstream exchange (NXNSAttack posture: duplicate pressure must
        # not multiply upstream work).  Keyed by the full lookup shape;
        # the holder future fans the leader's outcome to followers.
        self._inflight: dict = {}
        self._qf_inflight: dict = {}     # (name, qtype, resolver) -> fut
        self.coalesced = 0
        # set by the owning Recursion: the labelled
        # binder_recursion_coalesced_total child
        self.m_coalesced = None

    def _note_coalesced(self) -> None:
        self.coalesced += 1
        if self.m_coalesced is not None:
            self.m_coalesced.inc()

    def _build_wire(self, name: str, qtype: int,
                    qid: int) -> Tuple[bytearray, int]:
        """Query wire for one send: template (cached per name/qtype,
        RD=0, qid 0) + this send's qid + a fresh dns0x20 case mask.
        Returns (wire, qname_end_offset)."""
        key = (name, qtype)
        tmpl = self._tmpl.get(key)
        if tmpl is None:
            tmpl = make_query(name, qtype, qid=0, rd=False).encode()
            if len(self._tmpl) >= self._TMPL_MAX:
                self._tmpl.pop(next(iter(self._tmpl)))
            self._tmpl[key] = tmpl
        wire = bytearray(tmpl)
        wire[0] = qid >> 8
        wire[1] = qid & 0xFF
        # dns0x20: random case mask over the qname's alpha bytes (the
        # encoder emits lowercase; a fresh query's qname sits at offset
        # 12, uncompressed); the response must echo these exact bytes.
        # One getrandbits call covers the whole name.
        mask = random.getrandbits(256)
        off = 12
        while wire[off] != 0:
            ll = wire[off]
            for i in range(off + 1, off + 1 + ll):
                if 0x61 <= wire[i] <= 0x7A and (mask >> (i - 12)) & 1:
                    wire[i] -= 0x20
            off += 1 + ll
        return wire, off

    async def _get_port(self, host: str, port: int) -> _PortProto:
        loop = asyncio.get_running_loop()
        entry = self._ports.get((host, port))
        if entry is not None:
            e_loop, proto = entry
            if (e_loop is loop and proto.transport is not None
                    and not proto.transport.is_closing()):
                return proto
            _close_transport(proto)     # dead or from a previous loop
            self._ports.pop((host, port), None)
        transport, proto = await loop.create_datagram_endpoint(
            _PortProto, remote_addr=(host, port))
        # a concurrent first query may have created the port while we
        # awaited; keep the stored one and release ours, or every
        # 100-way PTR fan-out would leak sockets
        entry = self._ports.get((host, port))
        if entry is not None and entry[0] is loop \
                and entry[1].transport is not None \
                and not entry[1].transport.is_closing():
            transport.close()
            return entry[1]
        self._ports[(host, port)] = (loop, proto)
        return proto

    def case_mismatch_drops(self) -> int:
        """Upstream responses dropped for a mismatched dns0x20 question
        echo, summed across the pooled ports (peer-health
        introspection; the per-socket counters live on _PortProto)."""
        return sum(proto.case_mismatch_drops
                   for _e_loop, proto in self._ports.values())

    def close(self) -> None:
        for (_e_loop, proto) in self._ports.values():
            _close_transport(proto)
        self._ports.clear()

    def prune(self, keep: "set") -> None:
        """Close pooled sockets for upstreams no longer in the resolver
        set (long-lived processes see resolver churn; without pruning,
        one fd per address ever seen accumulates).  In-flight sockets
        are kept — the next prune after they drain gets them."""
        for key in list(self._ports):
            _e_loop, proto = self._ports[key]
            if key not in keep and not proto.pending:
                _close_transport(proto)
                del self._ports[key]

    async def lookup(self, name: str, qtype: int,
                     resolvers: Sequence[str],
                     error_threshold: Optional[int] = None
                     ) -> List[Record]:
        """Return the answers from the first NOERROR upstream response
        (decoded-record spelling; the forwarding hot path uses
        :meth:`lookup_raw` and never builds record objects)."""
        raw = await self.lookup_raw(name, qtype, resolvers,
                                    error_threshold)
        try:
            return Message.decode(raw).answers
        except Exception as e:  # noqa: BLE001 — malformed upstream bytes
            raise UpstreamError(f"bad upstream response: {e}")

    async def lookup_raw(self, name: str, qtype: int,
                         resolvers: Sequence[str],
                         error_threshold: Optional[int] = None
                         ) -> bytes:
        """Single-flight wrapper over :meth:`_lookup_raw_uncoalesced`:
        concurrent identical lookups (same name, type, resolver set and
        threshold) share ONE upstream exchange — the first caller runs
        the real dispatch, everyone else awaits its outcome.  Failures
        propagate to all waiters; a follower's cancellation never
        cancels the leader's exchange (shield)."""
        key = (name, qtype, tuple(resolvers), error_threshold)
        holder = self._inflight.get(key)
        if holder is not None and not holder.done():
            self._note_coalesced()
            return await asyncio.shield(holder)
        loop = asyncio.get_running_loop()
        holder = loop.create_future()
        # followers may never materialize: retrieve the exception so an
        # all-failed lookup with zero followers doesn't warn at GC
        holder.add_done_callback(
            lambda f: f.exception() if not f.cancelled() else None)
        self._inflight[key] = holder
        try:
            raw = await self._lookup_raw_uncoalesced(
                name, qtype, resolvers, error_threshold)
        except BaseException as e:
            if not holder.done():
                holder.set_exception(e)
            raise
        else:
            if not holder.done():
                holder.set_result(raw)
            return raw
        finally:
            if self._inflight.get(key) is holder:
                del self._inflight[key]

    async def _lookup_raw_uncoalesced(
            self, name: str, qtype: int, resolvers: Sequence[str],
            error_threshold: Optional[int] = None) -> bytes:
        """Return the first NOERROR upstream response as validated raw
        wire bytes.

        Validation is the id-multiplex + dns0x20 verbatim question echo
        (\\_PortProto) plus the header rcode/tc checks here; body
        structure is checked by whoever consumes the bytes (the splice
        walker, or Message.decode on the rebuild path).  Gives up once
        ``error_threshold`` upstreams have failed (default: all of
        them, matching mname-client's behavior of walking the whole
        list).

        Dispatch is breaker-aware and hedged (the serial-timeout fix):
        peers whose circuit breaker is open are dropped before any
        packet moves — an all-open set fails fast with a well-formed
        error instead of hanging — and after the first ``concurrency``
        upstreams, each further upstream is launched when a prior one
        FAILS *or* when the most recent one has been silent past its
        p95-based hedge delay, whichever is first.  A dead-but-
        unopened peer therefore costs one hedge stagger (~tens of ms),
        not the full 3 s timeout the reference pays per dead resolver.
        """
        if not resolvers:
            raise UpstreamError("no upstream resolvers")
        br = self.breakers
        if br is not None:
            usable = br.filter(resolvers)
            if not usable:
                raise UpstreamError(
                    "all upstream breakers open: "
                    + ", ".join(str(r) for r in resolvers[:4]))
            resolvers = usable
        threshold = (len(resolvers) if error_threshold is None
                     else min(error_threshold, len(resolvers)))

        if len(resolvers) == 1:
            # single upstream (the common cross-DC forward): skip the
            # task fan-out machinery entirely
            return await self._lookup_one_raw(name, qtype, resolvers[0])

        errors: List[str] = []
        alive = [False]     # any upstream returned a DNS response
        done_count = [0]
        started = [0]
        loop = asyncio.get_running_loop()
        winner: asyncio.Future = loop.create_future()
        progress = asyncio.Event()   # set on every per-resolver failure

        async def one(resolver: str) -> None:
            try:
                if winner.done():
                    return
                try:
                    raw = await self._query_one(name, qtype, resolver)
                except Exception as e:  # noqa: BLE001 — any failure
                    # counts against the threshold; an uncounted error
                    # (e.g. a malformed resolver string) would hang
                    # the lookup forever
                    errors.append(f"{resolver}: {e}")
                    progress.set()
                else:
                    alive[0] = True
                    rcode = raw[3] & 0x0F
                    tc = bool(raw[2] & 0x02)
                    if rcode == Rcode.NOERROR and tc:
                        # truncated: retry the same resolver over
                        # TCP before counting it as a failure
                        # (mname-client capability the reference
                        # relies on for large PTR/SRV answer sets,
                        # lib/recursion.js:253-279)
                        try:
                            raw = await self._query_one_tcp(
                                name, qtype, resolver)
                            rcode = raw[3] & 0x0F
                            tc = bool(raw[2] & 0x02)
                        except Exception as e:  # noqa: BLE001
                            errors.append(
                                f"{resolver}: tcp retry: {e}")
                            progress.set()
                            raw = None
                    if (raw is not None
                            and rcode == Rcode.NOERROR and not tc):
                        # full decode before the response can win
                        # the fan-out race: a body-malformed NOERROR
                        # must count as ONE resolver error and let
                        # another upstream win, not fail the lookup.
                        # (The single-upstream fast path skips this
                        # — with no alternative upstream, a decode
                        # failure ends the same way either side.)
                        ok = wire_walks(raw)
                        if ok:
                            try:
                                Message.decode(raw)
                            except Exception:  # noqa: BLE001
                                ok = False
                        if ok:
                            if not winner.done():
                                winner.set_result(raw)
                            return
                        errors.append(f"{resolver}: malformed body")
                        progress.set()
                        raw = None
                    if raw is not None:
                        errors.append(
                            f"{resolver}: "
                            + ("truncated" if tc
                               else f"rcode {Rcode.name(rcode)}"))
                        progress.set()
                if len(errors) >= threshold and not winner.done():
                    winner.set_exception(UpstreamError(
                        "; ".join(errors[-4:]), got_response=alive[0]))
            finally:
                done_count[0] += 1
                if (done_count[0] == len(resolvers)
                        and not winner.done()):
                    winner.set_exception(UpstreamError(
                        "; ".join(errors[-4:]) or "all upstreams failed",
                        got_response=alive[0]))

        burst = min(self.concurrency, len(resolvers))
        tasks = [asyncio.ensure_future(one(r))
                 for r in resolvers[:burst]]
        started[0] = burst
        errors_consumed = 0
        try:
            while started[0] < len(resolvers) and not winner.done():
                progress.clear()
                if len(errors) > errors_consumed:
                    # a prior upstream failed: launch the next one NOW
                    errors_consumed += 1
                else:
                    # hedge: give the most recently launched upstream
                    # its p95 (+headroom) to answer, then stop waiting
                    # for it alone.  No synchronization races here:
                    # failures only land during awaits, and the
                    # clear-check-wait sequence has none between them.
                    hedge = (br.hedge_delay(resolvers[started[0] - 1])
                             if br is not None else None)
                    waiter = asyncio.ensure_future(progress.wait())
                    try:
                        await asyncio.wait(
                            [winner, waiter], timeout=hedge,
                            return_when=asyncio.FIRST_COMPLETED)
                    finally:
                        waiter.cancel()
                    if winner.done():
                        break
                    if len(errors) > errors_consumed:
                        errors_consumed += 1
                tasks.append(asyncio.ensure_future(
                    one(resolvers[started[0]])))
                started[0] += 1
            return await winner
        finally:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

    def query_future(self, name: str, qtype: int,
                     resolver: str) -> Optional[asyncio.Future]:
        """Zero-coroutine send: build + send the query on the pooled
        port synchronously and return the response future (resolved by
        the shared protocol, timed out by its deadline sweep).  Returns
        None when the pooled port isn't ready (first query to an
        upstream, dead transport) — the caller takes the coroutine path,
        which (re)creates the port."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return None
        key = self._resolver_keys.get(resolver)
        if key is None:
            try:
                key = _parse_resolver(resolver)
            except ValueError:
                return None
            if len(self._resolver_keys) >= self._TMPL_MAX:
                self._resolver_keys.pop(next(iter(self._resolver_keys)))
            self._resolver_keys[resolver] = key
        entry = self._ports.get(key)
        if entry is None:
            return None
        e_loop, proto = entry
        if (e_loop is not loop or proto.transport is None
                or proto.transport.is_closing()):
            return None
        # single-flight on the zero-coroutine path too: a concurrent
        # identical forward reuses the pending wire future — each
        # caller's done-callback splices its own client id into the one
        # shared upstream answer.  (Each completion also records the
        # shared outcome on the breaker; N coalesced queries count as N
        # observations of the same exchange, which slightly overweights
        # it — harmless, and truthful about what clients experienced.)
        qf_key = (name, qtype, resolver)
        cur = self._qf_inflight.get(qf_key)
        if cur is not None and not cur.done():
            self._note_coalesced()
            return cur
        qid = random.getrandbits(16)
        while qid in proto.pending:
            qid = random.getrandbits(16)
        wire, off = self._build_wire(name, qtype, qid)
        fut: asyncio.Future = loop.create_future()
        proto.pending[qid] = (fut, bytes(wire[12:off + 5]),
                              loop.time() + self.timeout)
        self._qf_inflight[qf_key] = fut
        fut.add_done_callback(
            lambda f, k=qf_key:
            self._qf_inflight.pop(k)
            if self._qf_inflight.get(k) is f else None)
        proto._arm_sweep(loop, min(self.timeout / 2, 0.25))
        proto.transport.sendto(wire)
        return fut

    async def _lookup_one_raw(self, name: str, qtype: int,
                              resolver: str) -> bytes:
        """Single-upstream lookup with the same NOERROR/tc-retry policy
        as the fan-out path."""
        try:
            raw = await self._query_one(name, qtype, resolver)
        except Exception as e:  # noqa: BLE001 — same accounting as one()
            raise UpstreamError(f"{resolver}: {e}")
        rcode = raw[3] & 0x0F
        tc = bool(raw[2] & 0x02)
        if rcode == Rcode.NOERROR and tc:
            try:
                raw = await self._query_one_tcp(name, qtype, resolver)
            except Exception as e:  # noqa: BLE001
                # the UDP response arrived: the peer is alive even
                # though its TCP retry failed
                raise UpstreamError(f"{resolver}: tcp retry: {e}",
                                    got_response=True)
            rcode = raw[3] & 0x0F
            tc = bool(raw[2] & 0x02)
        if rcode == Rcode.NOERROR and not tc:
            if wire_walks(raw):
                return raw
            raise UpstreamError(f"{resolver}: malformed body",
                                got_response=True)
        raise UpstreamError(
            f"{resolver}: "
            + ("truncated" if tc else f"rcode {Rcode.name(rcode)}"),
            got_response=True)

    async def _query_one(self, name: str, qtype: int,
                         resolver: str) -> bytes:
        host, port = _parse_resolver(resolver)
        proto = await self._get_port(host, port)
        loop = asyncio.get_running_loop()
        # qid must be unique among this upstream's in-flight queries
        qid = random.getrandbits(16)
        while qid in proto.pending:
            qid = random.getrandbits(16)
        # Forwarded queries must not re-recurse: RD=0 in the template
        # (lib/recursion.js:259-261)
        wire, off = self._build_wire(name, qtype, qid)
        expect_q = bytes(wire[12:off + 5])   # qname + terminator + type/class
        fut: asyncio.Future = loop.create_future()
        proto.pending[qid] = (fut, expect_q, loop.time() + self.timeout)
        proto._arm_sweep(loop, min(self.timeout / 2, 0.25))
        if self.breakers is not None:
            # Breaker feedback rides the FUTURE, not this coroutine: a
            # hedged lookup cancels the losers' driver tasks the moment
            # a winner lands, but the losers' datagrams are still in
            # flight — their true outcome (response vs deadline-sweep
            # timeout) settles the future later, and THAT is what the
            # breaker must see, or a dead peer racing a healthy one
            # would never accumulate the failures that open its
            # breaker.  The pending entry is deliberately left in place
            # on cancellation below; the sweep (or a late response)
            # always settles and removes it within one timeout.
            sent_at = loop.time()

            def _outcome(f: "asyncio.Future",
                         resolver=resolver, sent_at=sent_at) -> None:
                if f.cancelled():
                    return      # outcome unknown: no evidence either way
                if f.exception() is not None:
                    self.breakers.record(resolver, False)
                else:
                    recv_t = getattr(f, "binder_recv_t", None)
                    self.breakers.record(
                        resolver, True,
                        (recv_t if recv_t is not None else loop.time())
                        - sent_at)

            fut.add_done_callback(_outcome)
        try:
            proto.transport.sendto(wire)
            if self.breakers is None:
                return await fut
            # shield: a hedged lookup cancels losing driver TASKS, and
            # a task awaiting a bare future cancels the future with it
            # — which would erase the in-flight query's real outcome.
            # Shielded, the wire future lives on; the deadline sweep
            # (or a late response) settles it, _outcome above records
            # the truth, and the settling path removes the pending
            # entry.
            return await asyncio.shield(fut)
        finally:
            # pop only our own SETTLED entry: after this qid was
            # released (answer delivered / socket failed), another
            # query may have re-used it before this finally ran
            cur = proto.pending.get(qid)
            if cur is not None and cur[0] is fut and fut.done():
                del proto.pending[qid]

    async def _query_one_tcp(self, name: str, qtype: int,
                             resolver: str) -> bytes:
        """RFC 1035 §4.2.2 framed query — the truncation fallback."""
        host, port = _parse_resolver(resolver)
        qid = random.randrange(0, 65536)
        query = make_query(name, qtype, qid=qid, rd=False)
        wire = query.encode()

        async def go() -> bytes:
            reader, writer = await asyncio.open_connection(host, port)
            try:
                writer.write(len(wire).to_bytes(2, "big") + wire)
                await writer.drain()
                hdr = await reader.readexactly(2)
                n = int.from_bytes(hdr, "big")
                raw = await reader.readexactly(n)
                if n < 12 or ((raw[0] << 8) | raw[1]) != qid:
                    raise WireTimeout("upstream TCP answer id mismatch")
                return raw
            finally:
                writer.close()
                try:
                    await writer.wait_closed()
                except (ConnectionResetError, BrokenPipeError, OSError):
                    pass

        return await asyncio.wait_for(go(), self.timeout)


class WireTimeout(Exception):
    pass
