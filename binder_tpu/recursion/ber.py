"""Minimal BER (ITU-T X.690) codec — just the subset LDAPv3 needs.

The reference consumes LDAP via the ``ufds`` npm package (an ldapjs
client, SURVEY §2.3); this rebuild owns the wire layer the same way it
owns the DNS codec.  Definite lengths only (LDAP forbids indefinite),
universal INTEGER/OCTET STRING/BOOLEAN/ENUMERATED/SEQUENCE/SET plus
application- and context-tagged forms.
"""
from __future__ import annotations

from typing import List, Tuple

# universal tags
INTEGER = 0x02
OCTET_STRING = 0x04
BOOLEAN = 0x01
NULL = 0x05
ENUMERATED = 0x0A
SEQUENCE = 0x30          # constructed
SET = 0x31               # constructed


class BerError(Exception):
    pass


def encode_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    out = b""
    while n:
        out = bytes([n & 0xFF]) + out
        n >>= 8
    return bytes([0x80 | len(out)]) + out


def tlv(tag: int, content: bytes) -> bytes:
    return bytes([tag]) + encode_len(len(content)) + content


def encode_int(value: int, tag: int = INTEGER) -> bytes:
    if value == 0:
        return tlv(tag, b"\x00")
    neg = value < 0
    out = b""
    v = value
    while True:
        out = bytes([v & 0xFF]) + out
        v >>= 8
        if (v == 0 and not neg and not (out[0] & 0x80)) or \
           (v == -1 and neg and (out[0] & 0x80)):
            break
    return tlv(tag, out)


def encode_str(s, tag: int = OCTET_STRING) -> bytes:
    if isinstance(s, str):
        s = s.encode("utf-8")
    return tlv(tag, s)


def encode_bool(b: bool) -> bytes:
    return tlv(BOOLEAN, b"\xff" if b else b"\x00")


def encode_seq(parts: List[bytes], tag: int = SEQUENCE) -> bytes:
    return tlv(tag, b"".join(parts))


def decode_tlv(data: bytes, off: int = 0) -> Tuple[int, bytes, int]:
    """Return (tag, content, offset-after) for the TLV at *off*."""
    if off + 2 > len(data):
        raise BerError("short TLV header")
    tag = data[off]
    if tag & 0x1F == 0x1F:
        raise BerError("multi-byte tags unsupported")
    length = data[off + 1]
    off += 2
    if length & 0x80:
        nlen = length & 0x7F
        if nlen == 0:
            raise BerError("indefinite length not allowed in LDAP")
        if nlen > 4 or off + nlen > len(data):
            raise BerError("bad long-form length")
        length = int.from_bytes(data[off:off + nlen], "big")
        off += nlen
    if off + length > len(data):
        raise BerError("TLV content overruns buffer")
    return tag, data[off:off + length], off + length


def decode_int(content: bytes) -> int:
    if not content:
        raise BerError("empty INTEGER")
    return int.from_bytes(content, "big", signed=True)


def decode_all(data: bytes) -> List[Tuple[int, bytes]]:
    """Decode a run of sibling TLVs (e.g. a SEQUENCE body)."""
    out = []
    off = 0
    while off < len(data):
        tag, content, off = decode_tlv(data, off)
        out.append((tag, content))
    return out


def frame_length(data: bytes) -> int:
    """Total bytes of the TLV starting at offset 0, or 0 if incomplete —
    for streaming message framing."""
    if len(data) < 2:
        return 0
    length = data[1]
    hdr = 2
    if length & 0x80:
        nlen = length & 0x7F
        if nlen == 0 or nlen > 4:
            raise BerError("bad frame length")
        if len(data) < 2 + nlen:
            return 0
        length = int.from_bytes(data[2:2 + nlen], "big")
        hdr = 2 + nlen
    total = hdr + length
    return total if len(data) >= total else 0
