"""In-process LDAPv3 server — the UFDS stand-in for tests and dev rigs.

The reference has **zero** automated coverage of its UFDS integration
(SURVEY §4: recursion is exercised only in real deployments).  This
server closes that gap the same way ``store/zk_testserver.py`` does for
ZooKeeper: a real asyncio server speaking the real wire protocol, backed
by an in-memory DIT, so :class:`~binder_tpu.recursion.ufds.LdapClient`
and the recursion refresh loop get protocol-level tests.

Supported: simple bind (credential check), search with base/one/sub
scopes and the filter subset in :mod:`binder_tpu.recursion.ufds`
(equality / presence / and / or / not), unbind.  Everything else gets
an ``unwillingToPerform`` result.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional

from binder_tpu.recursion import ber
from binder_tpu.recursion.ufds import (
    APP_BIND_REQUEST,
    APP_BIND_RESPONSE,
    APP_SEARCH_DONE,
    APP_SEARCH_ENTRY,
    APP_SEARCH_REQUEST,
    APP_UNBIND_REQUEST,
    SCOPE_BASE,
    SCOPE_ONE,
    SCOPE_SUB,
    eval_filter,
    normalize_dn,
)

RESULT_SUCCESS = 0
RESULT_PROTOCOL_ERROR = 2
RESULT_INVALID_CREDENTIALS = 49
RESULT_UNWILLING = 53


def _decode_filter(tag: int, content: bytes):
    """Wire filter → the same AST eval_filter consumes."""
    kind = tag & 0x1F
    if kind == 3:      # equalityMatch
        parts = ber.decode_all(content)
        return ("eq", parts[0][1].decode("utf-8", "replace").lower(),
                parts[1][1].decode("utf-8", "replace"))
    if kind == 7:      # present
        return ("present", content.decode("utf-8", "replace").lower())
    if kind in (0, 1):  # and / or
        return ("and" if kind == 0 else "or",
                [_decode_filter(t, c) for t, c in ber.decode_all(content)])
    if kind == 2:      # not
        t, c = ber.decode_all(content)[0]
        return ("not", _decode_filter(t, c))
    raise ber.BerError(f"unsupported filter choice {kind}")


class LdapTestServer:
    """``async with LdapTestServer(...) as srv: ...`` → ``srv.port``."""

    def __init__(self, *, bind_dn: str = "cn=root", password: str = "secret",
                 entries: Optional[Dict[str, Dict[str, List[str]]]] = None,
                 host: str = "127.0.0.1", ssl_context=None,
                 log: Optional[logging.Logger] = None) -> None:
        self.bind_dn = normalize_dn(bind_dn)
        self.password = password
        # dn (normalized) -> {attr(lower): [values]}
        self.entries: Dict[str, Dict[str, List[str]]] = {}
        for dn, attrs in (entries or {}).items():
            self.add_entry(dn, attrs)
        self.host = host
        self.ssl_context = ssl_context   # serve ldaps when set
        self.port: Optional[int] = None
        self.log = log or logging.getLogger("binder.ldap.testserver")
        self._server: Optional[asyncio.base_events.Server] = None
        self._writers: set = set()   # live client connections
        self.bind_count = 0
        self.search_count = 0

    def add_entry(self, dn: str, attrs: Dict[str, List[str]]) -> None:
        self.entries[normalize_dn(dn)] = {
            k.lower(): list(v) for k, v in attrs.items()}

    def remove_entry(self, dn: str) -> None:
        self.entries.pop(normalize_dn(dn), None)

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, 0, ssl=self.ssl_context)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # drop live clients first: a peer mid-TLS-handshake or
            # retrying connects keeps a handler alive, and on 3.12+
            # wait_closed() waits for all handlers — unbounded
            for w in list(self._writers):
                w.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5)
            except asyncio.TimeoutError:
                self.log.warning("ldap testserver: wait_closed timed out")
            self._server = None

    async def __aenter__(self) -> "LdapTestServer":
        await self.start()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    # -- connection handling --

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        buf = b""
        bound = False
        self._writers.add(writer)
        try:
            while True:
                total = ber.frame_length(buf)
                if not total:
                    chunk = await reader.read(65536)
                    if not chunk:
                        return
                    buf += chunk
                    continue
                frame, buf = buf[:total], buf[total:]
                tag, content, _ = ber.decode_tlv(frame)
                if tag != ber.SEQUENCE:
                    return
                parts = ber.decode_all(content)
                msgid = ber.decode_int(parts[0][1])
                op_tag, op = parts[1]

                if op_tag == APP_BIND_REQUEST:
                    bound = self._do_bind(writer, msgid, op)
                elif op_tag == APP_SEARCH_REQUEST:
                    if not bound:
                        self._send_result(writer, msgid, APP_SEARCH_DONE,
                                          RESULT_UNWILLING, "bind first")
                    else:
                        self._do_search(writer, msgid, op)
                elif op_tag == APP_UNBIND_REQUEST:
                    return
                else:
                    self._send_result(writer, msgid, APP_SEARCH_DONE,
                                      RESULT_UNWILLING, "unsupported op")
                await writer.drain()
        except (ber.BerError, ConnectionError, OSError) as e:
            self.log.debug("ldap testserver connection error: %s", e)
        finally:
            self._writers.discard(writer)
            writer.close()

    def _do_bind(self, writer, msgid: int, op: bytes) -> bool:
        self.bind_count += 1
        parts = ber.decode_all(op)
        ok = False
        diag = "invalid credentials"
        if len(parts) >= 3:
            dn = normalize_dn(parts[1][1].decode("utf-8", "replace"))
            pw = parts[2][1].decode("utf-8", "replace")
            if parts[2][0] != 0x80:
                diag = "only simple auth supported"
            else:
                ok = dn == self.bind_dn and pw == self.password
        self._send_result(writer, msgid, APP_BIND_RESPONSE,
                          RESULT_SUCCESS if ok else RESULT_INVALID_CREDENTIALS,
                          "" if ok else diag)
        return ok

    def _do_search(self, writer, msgid: int, op: bytes) -> None:
        self.search_count += 1
        try:
            parts = ber.decode_all(op)
            base = normalize_dn(parts[0][1].decode("utf-8", "replace"))
            scope = ber.decode_int(parts[1][1])
            flt = _decode_filter(*parts[6])
            want = [a.decode("utf-8", "replace").lower()
                    for _, a in ber.decode_all(parts[7][1])]
        except (ber.BerError, IndexError) as e:
            self._send_result(writer, msgid, APP_SEARCH_DONE,
                              RESULT_PROTOCOL_ERROR, str(e))
            return
        for dn, attrs in self.entries.items():
            if not _in_scope(dn, base, scope):
                continue
            if not eval_filter(flt, attrs):
                continue
            send = {k: v for k, v in attrs.items()
                    if not want or k in want}
            writer.write(self._encode_entry(msgid, dn, send))
        self._send_result(writer, msgid, APP_SEARCH_DONE, RESULT_SUCCESS, "")

    @staticmethod
    def _encode_entry(msgid: int, dn: str,
                      attrs: Dict[str, List[str]]) -> bytes:
        attr_parts = [
            ber.encode_seq([
                ber.encode_str(name),
                ber.encode_seq([ber.encode_str(v) for v in vals],
                               tag=ber.SET),
            ]) for name, vals in attrs.items()]
        entry = ber.encode_seq([
            ber.encode_str(dn),
            ber.encode_seq(attr_parts),
        ], tag=APP_SEARCH_ENTRY)
        return ber.encode_seq([ber.encode_int(msgid), entry])

    @staticmethod
    def _send_result(writer, msgid: int, tag: int, code: int,
                     diag: str) -> None:
        result = ber.encode_seq([
            ber.encode_int(code, tag=ber.ENUMERATED),
            ber.encode_str(""),      # matchedDN
            ber.encode_str(diag),
        ], tag=tag)
        writer.write(ber.encode_seq([ber.encode_int(msgid), result]))


def _in_scope(dn: str, base: str, scope: int) -> bool:
    if scope == SCOPE_BASE:
        return dn == base
    if not (dn == base or dn.endswith("," + base)):
        return False
    if scope == SCOPE_ONE:
        return dn != base and "," not in dn[:len(dn) - len(base) - 1]
    return scope == SCOPE_SUB
