"""Cross-datacenter recursion (port of lib/recursion.js)."""
from binder_tpu.recursion.client import (  # noqa: F401
    DnsClient,
    UpstreamError,
)
from binder_tpu.recursion.recursion import (  # noqa: F401
    Recursion,
    ResolverSource,
    StaticResolverSource,
)
from binder_tpu.recursion.ufds import (  # noqa: F401
    LdapClient,
    LdapError,
    UfdsResolverSource,
)
