"""Best-effort cross-datacenter recursive resolution.

Port of the reference's Recursion (``lib/recursion.js``): when a name (or
PTR address) misses the local cache and the client set RD, forward the
query to the binders of the datacenter named by the label in front of the
DNS domain — or, for PTR, to every binder we know of in parallel
(``lib/recursion.js:335-354``).

Structure preserved:
- **Resolver discovery** refreshes every 5 minutes (``:40,150-171``) from a
  pluggable source.  The reference hardcodes UFDS/LDAP (``listResolvers``);
  here that's the ``ResolverSource`` interface (SURVEY §7.1 step 6), with a
  config-driven ``StaticResolverSource`` and the real
  :class:`~binder_tpu.recursion.ufds.UfdsResolverSource` — a from-scratch
  LDAPv3 client selected when the config carries ``recursion.ufds.url``.
- **Best-effort init**: first discovery failure retries every 15 s forever
  and the service comes up anyway (``:183-196``); discovery errors after
  that are logged, never fatal (``:160-165``).
- **Self-filtering**: upstream addresses matching local NICs are dropped
  (30 s cached NIC list) so we don't recurse into ourselves (``:356-376``).
- **Answer rebuild**: upstream answers are re-added under the original
  query name, by record type, dropping unsupported types (``:299-323``);
  zero answers → REFUSED, same failover policy as the engine (``:292-296``).
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Sequence

from binder_tpu.dns.query import QueryCtx
from binder_tpu.dns.wire import (
    AAAARecord,
    ARecord,
    CNAMERecord,
    PTRRecord,
    Rcode,
    Record,
    SRVRecord,
    TXTRecord,
    Type,
)
from binder_tpu.recursion.client import DnsClient, UpstreamError
from binder_tpu.utils import netif

REFRESH_INTERVAL = 300.0   # 5 min (lib/recursion.js:40)
INIT_RETRY = 15.0          # lib/recursion.js:190
NIC_CACHE_TTL = 30.0       # lib/recursion.js:363
PTR_CONCURRENCY = 100      # lib/recursion.js:76-78


def _host_of(resolver: str) -> str:
    """Host part of 'ip', 'ip:port', or '[v6]:port' — bare IPv6 addresses
    contain colons and must not be split."""
    if resolver.startswith("["):
        return resolver[1:resolver.index("]")]
    if resolver.count(":") == 1:
        return resolver.partition(":")[0]
    return resolver


class ResolverSource:
    """Discovery interface: where do other datacenters' binders live?

    The reference implements this against UFDS:
    ``sdc-ldap search -b 'region=<region>, o=smartdc' objectclass=resolver``
    (``lib/recursion.js:16-19,202-219``).
    """

    async def init(self, zk_cache) -> None:
        """One-time bootstrap; may use the local cache (the reference
        resolves UFDS's own address through binder's ZK mirror,
        ``lib/recursion.js:105-127``).  Raise to trigger the 15 s retry."""

    async def list_resolvers(self, region_name: str) -> List[Dict[str, str]]:
        """Return [{"datacenter": dc, "ip": addr}, ...]."""
        raise NotImplementedError


class StaticResolverSource(ResolverSource):
    """Config-driven source: {"dc-name": ["ip", ...], ...}."""

    def __init__(self, dcs: Dict[str, Sequence[str]]) -> None:
        self._dcs = dcs

    async def list_resolvers(self, region_name: str) -> List[Dict[str, str]]:
        return [{"datacenter": dc, "ip": ip}
                for dc, ips in self._dcs.items() for ip in ips]


class Recursion:
    def __init__(self, *, zk_cache, dns_domain: str, datacenter_name: str,
                 region_name: str = "",
                 source: Optional[ResolverSource] = None,
                 ufds: Optional[dict] = None,
                 log: Optional[logging.Logger] = None,
                 nic_provider=netif.local_addresses,
                 client: Optional[DnsClient] = None,
                 ptr_client: Optional[DnsClient] = None) -> None:
        self.zk_cache = zk_cache
        self.dns_domain = dns_domain.lower()
        self.datacenter_name = datacenter_name
        self.region_name = region_name
        self.log = log or logging.getLogger("binder.recursion")
        if source is None:
            if ufds is not None and "dcs" in (ufds or {}):
                source = StaticResolverSource(ufds["dcs"])
            elif ufds is not None and ufds.get("url"):
                # the reference's real discovery path: UFDS over LDAP
                # (sapi template recursion.ufds, lib/recursion.js:129-148)
                from binder_tpu.recursion.ufds import UfdsResolverSource
                source = UfdsResolverSource(ufds, log=self.log)
            else:
                source = StaticResolverSource({})
        self.source = source
        self.nic_provider = nic_provider
        self.nsc = client or DnsClient(concurrency=2)
        # PTR fans out to every binder in parallel (lib/recursion.js:67-78)
        self.nsc_max = ptr_client or DnsClient(concurrency=PTR_CONCURRENCY)

        self.dcs: Dict[str, List[str]] = {}
        self._ready = asyncio.Event()
        self._nics: Optional[List[str]] = None
        self._nics_at = 0.0
        self._bg: List[asyncio.Task] = []
        self._closed = False
        try:
            asyncio.get_running_loop()
            self._spawn(self._init())
        except RuntimeError:
            pass  # no loop yet; caller drives via wait_ready()

    # -- lifecycle --

    def _spawn(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._bg.append(task)

    async def wait_ready(self) -> None:
        if not self._bg and not self._ready.is_set():
            self._spawn(self._init())
        await self._ready.wait()

    async def close(self) -> None:
        self._closed = True
        for t in self._bg:
            t.cancel()
        await asyncio.gather(*self._bg, return_exceptions=True)
        self.nsc.close()
        self.nsc_max.close()
        closer = getattr(self.source, "close", None)
        if closer is not None:
            await closer()

    async def _init(self) -> None:
        """Best-effort client init with 15 s retry
        (lib/recursion.js:93-198)."""
        while not self._closed:
            try:
                await self.source.init(self.zk_cache)
                await self.refresh()
            except Exception as e:  # noqa: BLE001 — best effort by design
                self.log.warning(
                    "Recursion: configured for recursive dns but unable to "
                    "initialize (%s); will try again in %ss, continuing "
                    "since recursive resolves are best effort", e,
                    INIT_RETRY)
                self._ready.set()
                await asyncio.sleep(INIT_RETRY)
                continue
            self.log.info("Recursion: done initing clients")
            self._ready.set()
            self._spawn(self._refresh_loop())
            return

    async def _refresh_loop(self) -> None:
        while not self._closed:
            await asyncio.sleep(REFRESH_INTERVAL)
            try:
                await self.refresh()
            except Exception as e:  # noqa: BLE001
                self.log.error("Recursion: error on refresh: %s", e)

    async def refresh(self) -> None:
        """Re-pull the per-DC resolver map (lib/recursion.js:202-249)."""
        resolvers = await self.source.list_resolvers(self.region_name)
        dcs: Dict[str, List[str]] = {}
        for r in resolvers:
            ips = dcs.setdefault(r["datacenter"], [])
            if r["ip"] not in ips:
                ips.append(r["ip"])
        self.log.debug("Recursion: setting recursion resolvers: %r", dcs)
        self.dcs = dcs
        # drop pooled upstream sockets for resolvers that left the set
        # (long-lived processes see resolver churn)
        from binder_tpu.recursion.client import _parse_resolver
        keep = {_parse_resolver(ip)
                for ips in dcs.values() for ip in ips}
        self.nsc.prune(keep)
        self.nsc_max.prune(keep)

    # -- the resolve path (lib/recursion.js:287-388) --

    def _my_addrs(self) -> List[str]:
        now = time.monotonic()
        if self._nics is None or now - self._nics_at > NIC_CACHE_TTL:
            self._nics = list(self.nic_provider())
            self._nics_at = now
        return self._nics

    async def resolve(self, query: QueryCtx) -> None:
        # decode_name lowercases wire names already; normalize again in
        # case a caller hands us a hand-built query (0x20-style mixed case)
        domain = query.name().lower()
        answers: List[Record] = []

        is_ptr = query.qtype() == Type.PTR

        def respond() -> None:
            if not answers:
                # see the REFUSED comment in the engine
                query.set_error(Rcode.REFUSED)
            else:
                for rec in answers:
                    rebuilt = self._rebuild(domain, rec)
                    if rebuilt is not None:
                        query.add_answer(rebuilt)
                if not query.response.answers:
                    query.set_error(Rcode.REFUSED)
            query.respond()

        if not is_ptr and not domain.endswith(self.dns_domain):
            # never forward names outside our domain to public DNS
            respond()
            return

        if not is_ptr:
            prefix = domain[:len(domain) - len(self.dns_domain) - 1]
            dc = prefix[prefix.rfind(".") + 1:]
            if dc not in self.dcs:
                respond()
                return
            upstreams = list(self.dcs[dc])
        else:
            upstreams = [ip for ips in self.dcs.values() for ip in ips]

        my_addrs = self._my_addrs()
        upstreams = [u for u in upstreams
                     if _host_of(u) not in my_addrs]
        if not upstreams:
            respond()
            return

        nsc = self.nsc_max if is_ptr else self.nsc
        try:
            answers = await nsc.lookup(
                domain, query.qtype(), upstreams,
                error_threshold=len(upstreams) if is_ptr else None)
        except UpstreamError as e:
            self.log.debug("recursion upstream error: %s", e)
            answers = []
        respond()

    def _rebuild(self, domain: str, rec: Record) -> Optional[Record]:
        """Re-create the upstream answer under the original query name,
        by type (lib/recursion.js:299-323)."""
        ttl = rec.ttl
        if isinstance(rec, ARecord):
            return ARecord(name=domain, ttl=ttl, address=rec.address)
        if isinstance(rec, AAAARecord):
            return AAAARecord(name=domain, ttl=ttl, address=rec.address)
        if isinstance(rec, (PTRRecord, CNAMERecord)):
            return type(rec)(name=domain, ttl=ttl, target=rec.target)
        if isinstance(rec, TXTRecord):
            return TXTRecord(name=domain, ttl=ttl, texts=rec.texts)
        if isinstance(rec, SRVRecord):
            return SRVRecord(name=domain, ttl=ttl, priority=rec.priority,
                             weight=rec.weight, port=rec.port,
                             target=rec.target)
        self.log.warning("recursion: upstream returned unsupported record "
                         "type %s, dropping", type(rec).__name__)
        return None
