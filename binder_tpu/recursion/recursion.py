"""Best-effort cross-datacenter recursive resolution.

Port of the reference's Recursion (``lib/recursion.js``): when a name (or
PTR address) misses the local cache and the client set RD, forward the
query to the binders of the datacenter named by the label in front of the
DNS domain — or, for PTR, to every binder we know of in parallel
(``lib/recursion.js:335-354``).

Structure preserved:
- **Resolver discovery** refreshes every 5 minutes (``:40,150-171``) from a
  pluggable source.  The reference hardcodes UFDS/LDAP (``listResolvers``);
  here that's the ``ResolverSource`` interface (SURVEY §7.1 step 6), with a
  config-driven ``StaticResolverSource`` and the real
  :class:`~binder_tpu.recursion.ufds.UfdsResolverSource` — a from-scratch
  LDAPv3 client selected when the config carries ``recursion.ufds.url``.
- **Best-effort init**: first discovery failure retries every 15 s forever
  and the service comes up anyway (``:183-196``); discovery errors after
  that are logged, never fatal (``:160-165``).
- **Self-filtering**: upstream addresses matching local NICs are dropped
  (30 s cached NIC list) so we don't recurse into ourselves (``:356-376``).
- **Answer rebuild**: upstream answers are re-added under the original
  query name, by record type, dropping unsupported types (``:299-323``);
  zero answers → REFUSED, same failover policy as the engine (``:292-296``).
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Sequence

from binder_tpu.dns.query import QueryCtx
from binder_tpu.dns.wire import (
    AAAARecord,
    ARecord,
    CNAMERecord,
    Message,
    PTRRecord,
    Rcode,
    Record,
    SRVRecord,
    TXTRecord,
    Type,
    WireError,
    skip_name,
    skip_record,
)
from binder_tpu.dns.server import HANDLED_ASYNC
from binder_tpu.recursion.client import DnsClient, UpstreamError
from binder_tpu.utils import netif

REFRESH_INTERVAL = 300.0   # 5 min (lib/recursion.js:40)
INIT_RETRY = 15.0          # lib/recursion.js:190
NIC_CACHE_TTL = 30.0       # lib/recursion.js:363
PTR_CONCURRENCY = 100      # lib/recursion.js:76-78


def _host_of(resolver: str) -> str:
    """Host part of 'ip', 'ip:port', or '[v6]:port' — bare IPv6 addresses
    contain colons and must not be split."""
    if resolver.startswith("["):
        return resolver[1:resolver.index("]")]
    if resolver.count(":") == 1:
        return resolver.partition(":")[0]
    return resolver


class ResolverSource:
    """Discovery interface: where do other datacenters' binders live?

    The reference implements this against UFDS:
    ``sdc-ldap search -b 'region=<region>, o=smartdc' objectclass=resolver``
    (``lib/recursion.js:16-19,202-219``).
    """

    async def init(self, zk_cache) -> None:
        """One-time bootstrap; may use the local cache (the reference
        resolves UFDS's own address through binder's ZK mirror,
        ``lib/recursion.js:105-127``).  Raise to trigger the 15 s retry."""

    async def list_resolvers(self, region_name: str) -> List[Dict[str, str]]:
        """Return [{"datacenter": dc, "ip": addr}, ...]."""
        raise NotImplementedError


class StaticResolverSource(ResolverSource):
    """Config-driven source: {"dc-name": ["ip", ...], ...}."""

    def __init__(self, dcs: Dict[str, Sequence[str]]) -> None:
        self._dcs = dcs

    async def list_resolvers(self, region_name: str) -> List[Dict[str, str]]:
        return [{"datacenter": dc, "ip": ip}
                for dc, ips in self._dcs.items() for ip in ips]


class Recursion:
    def __init__(self, *, zk_cache, dns_domain: str, datacenter_name: str,
                 region_name: str = "",
                 source: Optional[ResolverSource] = None,
                 ufds: Optional[dict] = None,
                 log: Optional[logging.Logger] = None,
                 nic_provider=netif.local_addresses,
                 client: Optional[DnsClient] = None,
                 ptr_client: Optional[DnsClient] = None,
                 breakers=None, collector=None, recorder=None) -> None:
        self.zk_cache = zk_cache
        self.dns_domain = dns_domain.lower()
        self.datacenter_name = datacenter_name
        self.region_name = region_name
        self.log = log or logging.getLogger("binder.recursion")
        # Per-peer circuit breakers (binder_tpu/policy/breaker.py),
        # shared by BOTH clients so a peer's health is one fact.  On by
        # default: a dead remote binder must cost a hedge stagger, not
        # the full serial timeout, and once its breaker is open it
        # costs nothing at all (docs/degradation.md).
        if breakers is None:
            from binder_tpu.policy.breaker import PeerBreakers
            breakers = PeerBreakers(collector=collector,
                                    recorder=recorder, log=self.log)
        self.breakers = breakers
        if source is None:
            if ufds is not None and "dcs" in (ufds or {}):
                source = StaticResolverSource(ufds["dcs"])
            elif ufds is not None and ufds.get("url"):
                # the reference's real discovery path: UFDS over LDAP
                # (sapi template recursion.ufds, lib/recursion.js:129-148)
                from binder_tpu.recursion.ufds import UfdsResolverSource
                source = UfdsResolverSource(ufds, log=self.log)
            else:
                source = StaticResolverSource({})
        self.source = source
        self.nic_provider = nic_provider
        self.nsc = client or DnsClient(concurrency=2, breakers=breakers)
        # PTR fans out to every binder in parallel (lib/recursion.js:67-78)
        self.nsc_max = ptr_client or DnsClient(concurrency=PTR_CONCURRENCY,
                                               breakers=breakers)
        # injected clients (tests) still get the shared breaker registry
        # unless they brought their own
        for c in (self.nsc, self.nsc_max):
            if c.breakers is None:
                c.breakers = breakers
        if collector is not None:
            m = collector.counter(
                "binder_recursion_coalesced_total",
                "concurrent identical recursions collapsed onto one "
                "upstream exchange (single-flight)").labelled()
            m.inc(0)
            for c in (self.nsc, self.nsc_max):
                if c.m_coalesced is None:
                    c.m_coalesced = m

        # federation layer (binder_tpu/federation): set via
        # Federation.attach().  upstream_budget is the per-query
        # upstream-work ceiling (NXNSAttack, arXiv:2005.09107) applied
        # to the slow path's fan-out list; None = unbounded (classic).
        self.federation = None
        self.upstream_budget: Optional[int] = None

        self.dcs: Dict[str, List[str]] = {}
        # monotonic instant of the last successful resolver-discovery
        # pull — peer-health introspection (a stale map past several
        # REFRESH_INTERVALs means discovery is failing quietly)
        self.last_refresh_mono: Optional[float] = None
        # set by the owning server (engine._after): enables the
        # zero-coroutine fast path, whose future callback must run the
        # metrics/log after-hook itself
        self.engine_after = None
        self._ready = asyncio.Event()
        self._nics: Optional[List[str]] = None
        self._nics_at = 0.0
        self._bg: List[asyncio.Task] = []
        self._closed = False
        try:
            asyncio.get_running_loop()
            self._spawn(self._init())
        except RuntimeError:
            pass  # no loop yet; caller drives via wait_ready()

    # -- lifecycle --

    def _spawn(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._bg.append(task)
        # completed tasks must not accumulate (the truncation-retry
        # path spawns per query)
        task.add_done_callback(self._bg_discard)

    def _bg_discard(self, task) -> None:
        try:
            self._bg.remove(task)
        except ValueError:
            pass

    async def wait_ready(self) -> None:
        if not self._bg and not self._ready.is_set():
            self._spawn(self._init())
        await self._ready.wait()

    async def close(self) -> None:
        self._closed = True
        for t in self._bg:
            t.cancel()
        await asyncio.gather(*self._bg, return_exceptions=True)
        self.nsc.close()
        self.nsc_max.close()
        closer = getattr(self.source, "close", None)
        if closer is not None:
            await closer()

    async def _init(self) -> None:
        """Best-effort client init with 15 s retry
        (lib/recursion.js:93-198)."""
        while not self._closed:
            try:
                await self.source.init(self.zk_cache)
                await self.refresh()
            except Exception as e:  # noqa: BLE001 — best effort by design
                self.log.warning(
                    "Recursion: configured for recursive dns but unable to "
                    "initialize (%s); will try again in %ss, continuing "
                    "since recursive resolves are best effort", e,
                    INIT_RETRY)
                self._ready.set()
                await asyncio.sleep(INIT_RETRY)
                continue
            self.log.info("Recursion: done initing clients")
            self._ready.set()
            self._spawn(self._refresh_loop())
            return

    async def _refresh_loop(self) -> None:
        while not self._closed:
            await asyncio.sleep(REFRESH_INTERVAL)
            try:
                await self.refresh()
            except Exception as e:  # noqa: BLE001
                self.log.error("Recursion: error on refresh: %s", e)

    async def refresh(self) -> None:
        """Re-pull the per-DC resolver map (lib/recursion.js:202-249)."""
        resolvers = await self.source.list_resolvers(self.region_name)
        dcs: Dict[str, List[str]] = {}
        for r in resolvers:
            ips = dcs.setdefault(r["datacenter"], [])
            if r["ip"] not in ips:
                ips.append(r["ip"])
        self.log.debug("Recursion: setting recursion resolvers: %r", dcs)
        self.dcs = dcs
        # drop pooled upstream sockets for resolvers that left the set
        # (long-lived processes see resolver churn)
        from binder_tpu.recursion.client import _parse_resolver
        keep = {_parse_resolver(ip)
                for ips in dcs.values() for ip in ips}
        self.nsc.prune(keep)
        self.nsc_max.prune(keep)
        self.last_refresh_mono = time.monotonic()

    def introspect(self) -> dict:
        """Peer-health section of the status snapshot
        (binder_tpu/introspect/status.py)."""
        dcs = {dc: list(ips) for dc, ips in self.dcs.items()}
        last = self.last_refresh_mono
        return {
            "ready": self._ready.is_set(),
            "region": self.region_name,
            "datacenters": dcs,
            "peer_count": sum(len(ips) for ips in dcs.values()),
            "last_refresh_age_seconds": (
                None if last is None else time.monotonic() - last),
            # dropped upstream responses whose dns0x20 question echo
            # mismatched — sustained growth means a spoofer or an
            # 0x20-incompatible peer
            "case_mismatch_drops": (self.nsc.case_mismatch_drops()
                                    + self.nsc_max.case_mismatch_drops()),
            # concurrent identical lookups collapsed by single-flight
            "coalesced": self.nsc.coalesced + self.nsc_max.coalesced,
            "upstream_budget": self.upstream_budget,
            # per-peer circuit breakers (docs/degradation.md): state,
            # failure runs, backoff, and the p95 behind the hedge delay
            "breakers": self.breakers.introspect(),
            "breakers_open": self.breakers.open_count(),
        }

    # -- the resolve path (lib/recursion.js:287-388) --

    def _my_addrs(self) -> List[str]:
        now = time.monotonic()
        if self._nics is None or now - self._nics_at > NIC_CACHE_TTL:
            self._nics = list(self.nic_provider())
            self._nics_at = now
        return self._nics

    def resolve(self, query: QueryCtx):
        """Entry point from the engine's recursion handoff.

        The dominant shape — forward query, one live upstream for the
        target DC, pooled port ready — is dispatched with ZERO coroutine
        machinery: the query goes out synchronously and a future
        callback completes it (splice-or-rebuild + respond + the
        engine's after hook), returning ``HANDLED_ASYNC``.  Everything
        else (PTR fan-out, multi-upstream DCs, cold ports, truncation
        retries) returns the coroutine the engine drives as a task."""
        # we ARE the recursive service for this shape: RA set on every
        # recursion-produced response, success or failure (the splice
        # path patches the same bit into forwarded wire)
        query.response.ra = True
        if self.engine_after is not None and query.qtype() != Type.PTR:
            domain = query.name().lower()
            if domain.endswith(self.dns_domain):
                prefix = domain[:len(domain) - len(self.dns_domain) - 1]
                dc = prefix[prefix.rfind(".") + 1:]
                ups = self.dcs.get(dc)
                if ups is not None and len(ups) == 1 \
                        and _host_of(ups[0]) not in self._my_addrs() \
                        and self.breakers.get(ups[0]).state == "closed":
                    # (non-closed breaker: the slow path owns the
                    # skip/probe/fail-fast policy via lookup_raw)
                    sent_at = time.monotonic()
                    fut = self.nsc.query_future(domain, query.qtype(),
                                                ups[0])
                    if fut is not None:
                        if self.federation is not None:
                            self.federation.note_forward(domain)
                        # attribution: "dispatch" = local work between
                        # the mirror miss and the upstream send
                        query.stamp("dispatch")
                        fut.add_done_callback(
                            lambda f: self._complete(query, domain, f,
                                                     sent_at, ups[0]))
                        return HANDLED_ASYNC
        return self._resolve_slow(query)

    def _complete(self, query: QueryCtx, domain: str,
                  fut: "asyncio.Future",
                  sent_at: Optional[float] = None,
                  upstream: Optional[str] = None) -> None:
        """Future callback finishing a fast-path forward: splice the
        validated upstream wire, or decode+rebuild for shapes the
        splice declines, or REFUSED on upstream failure — then run the
        engine's after hook (metrics/log)."""
        # Per-stage attribution for the 7.3ms p50 question (VERDICT r5
        # weak 6): how much of a recursive query is the wire round trip
        # vs sitting in the local event loop waiting for this callback?
        # The client stamps the datagram's arrival on the future
        # (binder_recv_t); the two spans are recorded separately so the
        # stage histograms/bench can name the owner.
        now = time.monotonic()
        recv_t = getattr(fut, "binder_recv_t", None)
        if sent_at is not None and recv_t is not None:
            query.record_phase("upstream-rtt",
                               (recv_t - sent_at) * 1000.0)
            query.record_phase("loop-wait", (now - recv_t) * 1000.0)
        # consume the whole dispatch→callback wait into its own cursor
        # phase so the splice/rebuild stamps below time only local work
        query.stamp("await")
        try:
            exc = fut.exception()
            raw_up = None if exc is not None else fut.result()
            if upstream is not None:
                # breaker feedback for the zero-coroutine path (the
                # coroutine paths record inside _query_one): a response
                # of any rcode is a live peer; an exception (timeout,
                # socket death) is a transport failure
                self.breakers.record(
                    upstream, raw_up is not None,
                    None if recv_t is None or sent_at is None
                    else recv_t - sent_at)
            if raw_up is None and self.federation is not None:
                # transport-level failure (timeout / socket death), not
                # a negative answer: the owning DC may be dark — serve
                # the cached foreign answer per the degradation policy
                if self.federation.serve_dark(query, domain):
                    if self.engine_after is not None:
                        self.engine_after(query)
                    return
            if raw_up is not None:
                rcode = raw_up[3] & 0x0F
                if raw_up[2] & 0x02 and rcode == Rcode.NOERROR:
                    # truncated: the TCP retry needs real async — hand
                    # the rare path to a task
                    self._spawn(self._finish_tcp(query, domain))
                    return
                if rcode != Rcode.NOERROR:
                    if self.federation is not None:
                        # a negative answer is still a LIVE peer
                        self.federation.note_success(
                            domain, query.qtype(), raw_up)
                    raw_up = None       # REFUSED shape below
            self._finish_wire(query, domain, raw_up)
        except Exception:  # noqa: BLE001 — callback context: must not leak
            self.log.exception("recursion completion failed")
            if not query.responded:
                query.set_error(Rcode.SERVFAIL)
                try:
                    query.respond()
                except OSError:
                    pass
            if self.engine_after is not None:
                self.engine_after(query)

    async def _finish_tcp(self, query: QueryCtx, domain: str) -> None:
        raw_up = None
        try:
            raw_up = await self.nsc._query_one_tcp(
                domain, query.qtype(), self._dc_upstream(domain))
            if raw_up is not None and (raw_up[3] & 0x0F) != Rcode.NOERROR:
                raw_up = None
        except Exception as e:  # noqa: BLE001 — best-effort retry
            self.log.debug("recursion tcp retry failed: %s", e)
            raw_up = None
        self._finish_wire(query, domain, raw_up)

    def _dc_upstream(self, domain: str) -> str:
        prefix = domain[:len(domain) - len(self.dns_domain) - 1]
        dc = prefix[prefix.rfind(".") + 1:]
        return self.dcs[dc][0]

    def _finish_wire(self, query: QueryCtx, domain: str,
                     raw_up: Optional[bytes]) -> None:
        """Shared tail: splice / rebuild / REFUSED, then the after hook."""
        answers: List[Record] = []
        if raw_up is not None and self.federation is not None:
            # the DC answered: mark it alive and deposit the answer in
            # the foreign cache (the dark-serve fallback's inventory)
            self.federation.note_success(domain, query.qtype(), raw_up)
        if raw_up is not None:
            if self._try_splice(query, raw_up):
                if self.engine_after is not None:
                    self.engine_after(query)
                return
            try:
                answers = Message.decode(raw_up).answers
            except WireError as e:
                self.log.warning("recursion: undecodable upstream "
                                 "response (%s)", e)
        self._respond_rebuilt(query, domain, answers)
        if self.engine_after is not None:
            self.engine_after(query)

    def _respond_rebuilt(self, query: QueryCtx, domain: str,
                         answers: List[Record]) -> None:
        if not answers:
            # see the REFUSED comment in the engine
            query.set_error(Rcode.REFUSED)
        else:
            for rec in answers:
                rebuilt = self._rebuild(domain, rec)
                if rebuilt is not None:
                    query.add_answer(rebuilt)
            if not query.response.answers:
                query.set_error(Rcode.REFUSED)
        query.stamp("rebuild")   # decode+rebuild path (splice declined)
        query.respond()

    async def _resolve_slow(self, query: QueryCtx) -> None:
        # decode_name lowercases wire names already; normalize again in
        # case a caller hands us a hand-built query (0x20-style mixed case)
        domain = query.name().lower()
        answers: List[Record] = []

        is_ptr = query.qtype() == Type.PTR

        if not is_ptr and not domain.endswith(self.dns_domain):
            # never forward names outside our domain to public DNS
            self._respond_rebuilt(query, domain, answers)
            return

        if not is_ptr:
            prefix = domain[:len(domain) - len(self.dns_domain) - 1]
            dc = prefix[prefix.rfind(".") + 1:]
            if dc not in self.dcs:
                self._respond_rebuilt(query, domain, answers)
                return
            upstreams = list(self.dcs[dc])
        else:
            upstreams = [ip for ips in self.dcs.values() for ip in ips]

        my_addrs = self._my_addrs()
        upstreams = [u for u in upstreams
                     if _host_of(u) not in my_addrs]
        if not upstreams:
            self._respond_rebuilt(query, domain, answers)
            return

        # per-query upstream-work budget (NXNSAttack, arXiv:2005.09107):
        # one client query may touch at most this many upstreams — the
        # PTR fan-out across every DC is exactly the amplification shape
        # the budget exists to cap
        budget = self.upstream_budget
        if budget is not None and len(upstreams) > budget:
            upstreams = upstreams[:budget]
            query.log_ctx["budget_clamped"] = True
            if self.federation is not None:
                self.federation.m_budget.inc()

        nsc = self.nsc_max if is_ptr else self.nsc
        raw_up = None
        query.stamp("dispatch")
        if self.federation is not None and not is_ptr:
            self.federation.note_forward(domain)
        try:
            raw_up = await nsc.lookup_raw(
                domain, query.qtype(), upstreams,
                error_threshold=len(upstreams) if is_ptr else None)
            # whole awaited lookup (RTT + loop scheduling + any retries)
            # — the slow path can't split them like the future fast path
            query.stamp("upstream")
            if self.federation is not None and not is_ptr:
                self.federation.note_success(domain, query.qtype(), raw_up)
        except UpstreamError as e:
            self.log.debug("recursion upstream error: %s", e)
            if (self.federation is not None and not is_ptr
                    and not e.got_response
                    and self.federation.serve_dark(query, domain)):
                # transport-dark DC: stale-served (or withheld) from
                # the foreign cache — never a timeout
                return
        if raw_up is not None:
            # Raw splice (the hot path): the upstream answer — already
            # validated by id + dns0x20 question echo + NOERROR — is
            # forwarded as wire bytes with this client's id, RD bit, and
            # question case patched in, skipping decode and re-encode
            # entirely.  The reference rebuilds every record per type
            # per query (lib/recursion.js:299-323); splicing leaves the
            # semantics identical (differential-tested, byte-equal for
            # binder-shaped upstreams) at a fraction of the cost.
            # Shapes the splice can't prove safe fall back to the
            # decode+rebuild path below.
            if self._try_splice(query, raw_up):
                return
            try:
                answers = Message.decode(raw_up).answers
            except WireError as e:
                self.log.warning("recursion: undecodable upstream "
                                 "response (%s)", e)
                answers = []
        self._respond_rebuilt(query, domain, answers)

    def _try_splice(self, query: QueryCtx, up: bytes) -> bool:
        """Forward the upstream wire directly: patch id + RD + question
        case, keep (or strip) the EDNS OPT to match the client, send.

        Returns False — leaving the decode+rebuild path authoritative —
        for every shape it can't prove equivalent to the rebuild:
        multi-question, authority records, non-OPT additionals (the
        rebuild drops those), structural walk failures, a needed-but-
        absent OPT, an answer that would exceed the client's UDP
        ceiling, or a query whose log line needs decoded record detail
        (the logged posture keeps full answer summaries)."""
        raw = query.raw
        req = query.request
        if (raw is None or query.want_log_detail
                or len(req.questions) != 1):
            return False
        if query.latency_ms() > 1000.0:
            # the slow-query WARNING (SLOW_QUERY_MS) fires even with
            # query_log off and needs decoded answer summaries — a
            # forward that is ALREADY slow takes the rebuild path so
            # its log line carries them
            return False
        if len(up) < 12 or up[4:6] != b"\x00\x01" \
                or up[8:10] != b"\x00\x00":
            return False                # question/authority shape
        # walk the upstream question (uncompressed by construction —
        # our client sent it; the echo was verified byte-exact)
        q_end = skip_name(up, 12)
        if q_end is None or q_end + 4 > len(up):
            return False
        q_end += 4
        # client question section from the request wire: must be the
        # same name modulo 0x20 case, same type/class, same length
        cq_end = skip_name(raw, 12)
        if cq_end is None or cq_end + 4 > len(raw):
            return False
        cq_end += 4
        if cq_end != q_end \
                or raw[12:cq_end].lower() != up[12:q_end].lower():
            return False
        ancount = (up[6] << 8) | up[7]
        arcount = (up[10] << 8) | up[11]
        pos = q_end
        for _ in range(ancount):
            nxt = skip_record(up, pos)
            if nxt is None:
                return False
            pos = nxt[0]
        opt_start = None
        for i in range(arcount):
            start = pos
            nxt = skip_record(up, pos)
            if nxt is None:
                return False
            pos, rtype = nxt
            if rtype != Type.OPT:
                # the rebuild path drops non-OPT additionals; splicing
                # them through would diverge — decline
                return False
            if i != arcount - 1:
                return False            # OPT must be the final record
            opt_start = start
        if pos != len(up):
            return False                # trailing bytes
        if req.edns is not None:
            if opt_start is None:
                return False            # rebuild would add the echo OPT
            tail = up[q_end:]
            new_ar = arcount
        elif opt_start is not None:
            tail = up[q_end:opt_start]  # client spoke no EDNS: strip
            new_ar = arcount - 1
        else:
            tail = up[q_end:]
            new_ar = arcount
        # header: client id, upstream flags with the client's RD echoed
        # (we forward with RD=0), RA set — WE are the recursive service
        # here; the upstream answered authoritatively with its own RA
        # clear — and counts with the OPT adjustment
        flags2 = (up[2] & 0xFE) | (0x01 if req.rd else 0)
        wire = (req.id.to_bytes(2, "big")
                + bytes((flags2, up[3] | 0x80))
                + up[4:10] + new_ar.to_bytes(2, "big")
                + raw[12:q_end] + tail)
        if query.udp_semantics and len(wire) > req.max_udp_payload():
            return False                # truncation: rebuild path owns it
        query.response.rcode = up[3] & 0x0F   # for metrics
        query.log_ctx["spliced"] = True
        # attribution: local splice work only (the upstream wait was
        # consumed by the "await"/"upstream" stamps upstream of here)
        query.stamp("splice")
        query.respond_raw(wire)
        return True

    def _rebuild(self, domain: str, rec: Record) -> Optional[Record]:
        """Re-create the upstream answer under the original query name,
        by type (lib/recursion.js:299-323)."""
        ttl = rec.ttl
        if isinstance(rec, ARecord):
            return ARecord(name=domain, ttl=ttl, address=rec.address)
        if isinstance(rec, AAAARecord):
            return AAAARecord(name=domain, ttl=ttl, address=rec.address)
        if isinstance(rec, (PTRRecord, CNAMERecord)):
            return type(rec)(name=domain, ttl=ttl, target=rec.target)
        if isinstance(rec, TXTRecord):
            return TXTRecord(name=domain, ttl=ttl, texts=rec.texts)
        if isinstance(rec, SRVRecord):
            return SRVRecord(name=domain, ttl=ttl, priority=rec.priority,
                             weight=rec.weight, port=rec.port,
                             target=rec.target)
        self.log.warning("recursion: upstream returned unsupported record "
                         "type %s, dropping", type(rec).__name__)
        return None
