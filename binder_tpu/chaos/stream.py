"""Stream-lane (TCP) fault clients for the chaos DSL.

The PR-5 stream-lane overhaul (accept fast path, coalesced pipelined
writes, slow-reader disconnect) is only trustworthy if misbehaving TCP
peers are injected the same way PR 4 injected session loss and upstream
packet faults.  Three client shapes cover the connection-table hazards:

- ``tcp-slow-reader conns=N queries=M hold_ms=H`` — N connections each
  pipeline M queries with a tiny receive window and never read a byte;
  the server must disconnect each at ``MAX_TCP_WRITE_BUFFER``
  (``binder_tcp_slow_reader_drops``), never buffer unboundedly.
- ``tcp-half-close queries=M`` — send M queries then ``SHUT_WR`` (a
  legitimate RFC 7766 client shape): every owed response must still
  arrive, and the slot must be reclaimed afterwards.
- ``tcp-rst conns=N`` — send a partial frame (header promising more
  bytes than follow) then RST via ``SO_LINGER(0)``: the connection
  table must shed the carcass without wedging.

Every fault is driven against a live server's host/port
(``ChaosDriver(tcp_target=...)``); assertions live in the callers
(tests/test_tcp_stream.py, ``make tcp-smoke``) — this module only
injects.
"""
from __future__ import annotations

import asyncio
import socket
import struct

from binder_tpu.dns import Type, make_query

#: per-socket I/O budget: a fault client must never outlive the
#: incident window it was scripted into
_IO_TIMEOUT_S = 5.0


async def run_stream_fault(action: str, host: str, port: int,
                           qname: str, **kwargs) -> None:
    """Dispatch one DSL stream action (the ChaosDriver entry)."""
    if action == "tcp-slow-reader":
        await slow_reader(host, port, qname,
                          conns=int(kwargs.get("conns", 1)),
                          queries=int(kwargs.get("queries", 256)),
                          hold_ms=float(kwargs.get("hold_ms", 1000)))
    elif action == "tcp-half-close":
        await half_close(host, port, qname,
                         queries=int(kwargs.get("queries", 1)))
    elif action == "tcp-rst":
        await rst_mid_frame(host, port,
                            conns=int(kwargs.get("conns", 1)))
    else:
        raise ValueError(f"unknown stream fault {action!r}")


async def slow_reader(host: str, port: int, qname: str, *,
                      conns: int = 1, queries: int = 256,
                      hold_ms: float = 1000.0) -> None:
    """Pipeline queries and never read responses.  The tiny client
    receive window keeps the kernel from absorbing the backlog, so the
    server's write buffer grows toward its cap."""
    loop = asyncio.get_running_loop()
    wire = make_query(qname, Type.A, qid=0, edns_payload=4096).encode()
    frame = struct.pack(">H", len(wire)) + wire
    block = frame * min(64, max(1, queries))
    rounds = max(1, (queries + 63) // 64)
    socks = []
    try:
        for _ in range(max(1, conns)):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
            s.setblocking(False)
            try:
                await asyncio.wait_for(
                    loop.sock_connect(s, (host, port)), _IO_TIMEOUT_S)
            except (OSError, asyncio.TimeoutError):
                s.close()
                continue
            socks.append(s)
        for s in socks:
            try:
                for _ in range(rounds):
                    await asyncio.wait_for(loop.sock_sendall(s, block),
                                           _IO_TIMEOUT_S)
            except (OSError, asyncio.TimeoutError):
                pass   # disconnected (the fault landed) or wedged: done
        await asyncio.sleep(hold_ms / 1000.0)
    finally:
        for s in socks:
            s.close()


async def half_close(host: str, port: int, qname: str, *,
                     queries: int = 1) -> None:
    """Send, SHUT_WR, then keep reading: the legitimate one-shot client
    shape the stream lane must serve out rather than drop."""
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), _IO_TIMEOUT_S)
    except (OSError, asyncio.TimeoutError):
        return
    try:
        for i in range(max(1, queries)):
            wire = make_query(qname, Type.A, qid=i + 1).encode()
            writer.write(struct.pack(">H", len(wire)) + wire)
        await writer.drain()
        writer.write_eof()
        got = 0
        try:
            while got < max(1, queries):
                hdr = await asyncio.wait_for(reader.readexactly(2),
                                             _IO_TIMEOUT_S)
                await asyncio.wait_for(
                    reader.readexactly(int.from_bytes(hdr, "big")),
                    _IO_TIMEOUT_S)
                got += 1
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                ConnectionResetError):
            pass
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass


async def rst_mid_frame(host: str, port: int, *, conns: int = 1) -> None:
    """Open, send a torn frame (length prefix promising more bytes than
    follow), then RST: the connection-table-wedge probe."""
    loop = asyncio.get_running_loop()
    for _ in range(max(1, conns)):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setblocking(False)
        try:
            await asyncio.wait_for(loop.sock_connect(s, (host, port)),
                                   _IO_TIMEOUT_S)
            await asyncio.wait_for(
                loop.sock_sendall(s, b"\x01\x00abc"), _IO_TIMEOUT_S)
            # give the torn frame a moment to land in the server's read
            # buffer before tearing the connection down under it
            await asyncio.sleep(0.05)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                         struct.pack("ii", 1, 0))
        except (OSError, asyncio.TimeoutError):
            pass
        s.close()
