"""FaultPlan: a scriptable fault-injection schedule.

The degraded paths are only trustworthy if they are *tested* the way
the hot path is benched — ZDNS-style measurement discipline applied to
failure.  A :class:`FaultPlan` is a timeline of fault actions plus the
live upstream-fault state, injectable into the fake store, the ZK test
server, and the chaos upstream (``chaos/upstream.py``), and scriptable
from three places: unit tests (build it in code), ``make chaos-smoke``
(the DSL below), and the bench's degraded axis (a ``chaos`` config
block, ``main.py``).

DSL — one action per line (``;`` also separates), ``#`` comments::

    at 0.5  lose-session            # store goes dark, mirror starts aging
    at 1.0  watch-storm n=600       # mutation burst through the store
    at 2.0  loop-stall ms=120       # synchronous event-loop stall
    at 2.5  upstream loss=0.3 delay_ms=40 dup=0.05
    at 3.0  tcp-slow-reader conns=2 queries=512   # never reads answers
    at 3.5  tcp-half-close queries=3    # send then SHUT_WR
    at 3.8  tcp-rst conns=2             # torn frame + RST
    at 4.0  expire-session          # loss + immediate re-establish
    at 4.5  shard-kill shard=0      # SIGKILL a serving shard worker
    at 4.7  worker-roll shard=0     # zero-downtime drain-and-replace
    at 4.8  rrl-flood n=400         # spoofed-prefix UDP burst
    at 5.0  restore-session         # plain re-establish
    at 5.2  corrupt-answer          # flip a byte in a compiled wire
    at 5.4  drop-reverse            # delete one PTR map entry
    at 5.6  skew-replica shard=0    # suppress one worker delta frame
    at 6.0  upstream clear          # all upstream faults off

Actions
-------
- ``lose-session`` / ``restore-session`` / ``expire-session`` — drive
  the store's session test hooks (``FakeStore.lose_session`` /
  ``start_session`` / ``expire_session``; the ZK test server's
  ``drop_connections`` / ``expire_session`` via duck typing).
- ``watch-storm n=N`` — apply N mutations through the driver's
  ``mutate`` callback (the caller owns what a mutation writes).
- ``loop-stall ms=M`` — block the event loop synchronously for M ms
  (what a GC pause / runaway callback does to serving).
- ``upstream k=v ...`` — set live fault knobs consumed by
  :class:`~binder_tpu.chaos.upstream.ChaosUpstream`: ``loss`` (drop
  probability), ``delay_ms`` (response delay, making a slow peer),
  ``dup`` (duplicate-response probability), ``truncate`` (1 = answer
  TC=1 with no answers, forcing the TCP retry path), ``dead`` (1 =
  drop everything).  ``upstream clear`` resets all of them.
- ``tcp-slow-reader`` / ``tcp-half-close`` / ``tcp-rst`` — misbehaving
  stream-lane clients driven at the driver's ``tcp_target``
  (``chaos/stream.py``): a pipelining client that never reads (must be
  disconnected at the write-buffer cap), a send-then-SHUT_WR client
  (must still get its answers), and a torn-frame RST (must never wedge
  the connection table).
- ``shard-kill [shard=I]`` — SIGKILL one shard worker mid-load via the
  driver's ``shard_target`` (the supervisor's ``kill_shard``;
  ``shard`` omitted or -1 picks a live worker at random).  The
  acceptance invariant is the supervisor's: the kernel re-hashes the
  dead socket's share to the survivors at once, and the respawned
  worker catches up from snapshot (binder_tpu/shard).
- ``worker-roll [shard=I]`` — request a zero-downtime drain-and-
  replace cycle via the driver's ``roll_target`` (the supervisor's
  ``request_roll``; ``shard`` omitted or -1 rolls every shard in
  sequence).  Unlike ``shard-kill`` this is the *cooperative* path:
  the acceptance invariant is zero query loss — replacement converges
  from snapshot and joins the reuseport group BEFORE the incumbent is
  drained, one shard at a time.  Rolling mid-incident (after a
  ``lose-session`` or during an ``rrl-flood``) is exactly the
  operator reality the chaos smoke pins.
- ``rrl-flood [n=N] [qname=...]`` — synchronous burst of N (default
  400) well-formed UDP queries from spoofed attacker-prefix source
  addresses (the same 127/8 prefixes ``tools/hostile.py`` uses, so
  per-prefix RRL isolates them from the 127.0.0/24 measurement
  client), fired at the driver's ``udp_target``.  Replies are never
  read — the flood models reflection-attack ammunition, and the
  assertable outcome is on the server: ``binder_rrl_*`` counters move,
  the legit client's goodput survives.
- ``corrupt-answer [qname=...]`` / ``drop-reverse [ip=...]`` /
  ``skew-replica [shard=I] [frames=N]`` — verify-plane faults (ISSUE
  16), dispatched by method name at the driver's ``verify_target``
  (the :class:`BinderServer` for the table corruptions, the shard
  supervisor for the mutation-log skew).  Each breaks serving state
  WITHOUT firing an invalidation — the sampled audit (compiled-bytes,
  ptr-coherence) and the digest frames (replica-digest) are the only
  things that can catch them, which is the point: the chaos action
  proves the checker's detection, not the datapath's tolerance.

Determinism: the plan carries its own seeded RNG; two runs with the
same seed inject byte-identical fault decisions.
"""
from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Callable, List, Optional, Tuple

ACTIONS = ("lose-session", "restore-session", "expire-session",
           "watch-storm", "loop-stall", "upstream",
           "tcp-slow-reader", "tcp-half-close", "tcp-rst",
           "shard-kill", "worker-roll", "rrl-flood",
           "corrupt-answer", "drop-reverse", "skew-replica")
STREAM_ACTIONS = ("tcp-slow-reader", "tcp-half-close", "tcp-rst")
#: spoofed-source /24s the rrl-flood action binds (Linux accepts any
#: 127/8 address unconfigured) — the SAME prefixes tools/hostile.py
#: floods from, so one RRL allowlist/bucket story covers both harnesses
FLOOD_PREFIXES = ("127.66.7", "127.66.8", "127.99.1", "127.99.2")
#: verify-plane faults, dispatched by method name at ``verify_target``
VERIFY_ACTIONS = ("corrupt-answer", "drop-reverse", "skew-replica")


class UpstreamFaults:
    """Live fault state the chaos upstream consults per packet."""

    __slots__ = ("loss", "delay_ms", "dup", "truncate", "dead")

    def __init__(self) -> None:
        self.clear()

    def clear(self) -> None:
        self.loss = 0.0
        self.delay_ms = 0.0
        self.dup = 0.0
        self.truncate = False
        self.dead = False

    def set(self, **kw) -> None:
        for key, val in kw.items():
            if key == "clear":
                self.clear()
            elif key in ("loss", "delay_ms", "dup"):
                setattr(self, key, float(val))
            elif key in ("truncate", "dead"):
                setattr(self, key, bool(int(val)))
            else:
                raise ValueError(f"unknown upstream fault knob {key!r}")

    def snapshot(self) -> dict:
        return {"loss": self.loss, "delay_ms": self.delay_ms,
                "dup": self.dup, "truncate": self.truncate,
                "dead": self.dead}


class FaultPlan:
    """Timeline of (t_offset_seconds, action, kwargs) + live state."""

    def __init__(self, seed: int = 0) -> None:
        self.timeline: List[Tuple[float, str, dict]] = []
        self.upstream = UpstreamFaults()
        self.rng = random.Random(seed)
        self.seed = seed

    def at(self, t: float, action: str, **kwargs) -> "FaultPlan":
        """Append one scheduled action (builder style, chainable)."""
        if action not in ACTIONS:
            raise ValueError(f"unknown chaos action {action!r}")
        self.timeline.append((float(t), action, kwargs))
        self.timeline.sort(key=lambda e: e[0])
        return self

    @property
    def duration(self) -> float:
        return self.timeline[-1][0] if self.timeline else 0.0

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultPlan":
        """Parse the DSL above.  Raises ValueError with the offending
        fragment on any malformed line — a chaos script that silently
        does nothing is worse than none."""
        plan = cls(seed=seed)
        for raw_line in spec.replace(";", "\n").splitlines():
            line = raw_line.split("#", 1)[0].strip()
            if not line:
                continue
            toks = line.split()
            if len(toks) < 3 or toks[0] != "at":
                raise ValueError(f"chaos spec: expected "
                                 f"'at <t> <action> ...': {line!r}")
            try:
                t = float(toks[1])
            except ValueError:
                raise ValueError(f"chaos spec: bad time {toks[1]!r}")
            action = toks[2]
            kwargs: dict = {}
            for tok in toks[3:]:
                if tok == "clear":
                    kwargs["clear"] = True
                    continue
                if "=" not in tok:
                    raise ValueError(f"chaos spec: expected k=v, "
                                     f"got {tok!r}")
                k, v = tok.split("=", 1)
                try:
                    kwargs[k] = float(v) if "." in v else int(v)
                except ValueError:
                    # non-numeric values are strings (verify-plane
                    # selectors: qname=..., ip=...); empty is still
                    # malformed
                    if not v:
                        raise ValueError(f"chaos spec: bad value {tok!r}")
                    kwargs[k] = v
            plan.at(t, action, **kwargs)
        return plan


class ChaosDriver:
    """Binds a :class:`FaultPlan` to live targets and runs it.

    Targets are all optional — a plan driven only at an upstream needs
    no store, and vice versa.  ``mutate`` is called ``mutate(i)`` per
    watch-storm mutation; the caller decides what churn means for its
    fixture.  Every applied action is flight-recorded
    (``chaos-inject``) so a soak's failure report can line the
    injected faults up against the observed transitions.
    """

    def __init__(self, plan: FaultPlan, *, store=None,
                 mutate: Optional[Callable[[int], None]] = None,
                 tcp_target: Optional[Tuple[str, int, str]] = None,
                 udp_target: Optional[Tuple[str, int, str]] = None,
                 shard_target: Optional[Callable[[int], object]] = None,
                 roll_target: Optional[Callable[[int], object]] = None,
                 verify_target=None,
                 recorder=None,
                 log: Optional[logging.Logger] = None) -> None:
        self.plan = plan
        self.store = store
        self.mutate = mutate
        # (host, port, qname) the stream faults connect to; None skips
        # tcp-* actions with a warning (a plan driven only at the store
        # needs no live listener)
        self.tcp_target = tcp_target
        # (host, port, qname) the rrl-flood spoofed burst fires at;
        # falls back to tcp_target (binder serves both lanes on one
        # port) when unset
        self.udp_target = udp_target
        # shard-kill sink: the supervisor's kill_shard(index) (index -1
        # = random live worker); None skips with a warning
        self.shard_target = shard_target
        # worker-roll sink: request_roll(shard) on the supervisor
        # (shard -1 = roll every shard in sequence)
        self.roll_target = roll_target
        # verify-plane fault sink: corrupt_answer/drop_reverse on a
        # BinderServer, skew_replica on a shard supervisor — dispatch
        # is by method name, so either (or a test double) fits
        self.verify_target = verify_target
        self.recorder = recorder
        self.log = log or logging.getLogger("binder.chaos")
        self.applied: List[Tuple[float, str]] = []
        self.started_mono: Optional[float] = None
        self._stream_tasks: set = set()

    # -- action dispatch --

    def apply(self, action: str, kwargs: dict) -> None:
        """Apply one action NOW (also the unit-test entry — no loop
        needed)."""
        if action == "upstream":
            self.plan.upstream.set(**kwargs)
        elif action == "watch-storm":
            n = int(kwargs.get("n", 100))
            if self.mutate is None:
                self.log.warning("chaos: watch-storm with no mutate "
                                 "target; skipped")
            else:
                for i in range(n):
                    self.mutate(i)
        elif action == "loop-stall":
            time.sleep(float(kwargs.get("ms", 100)) / 1000.0)
        elif action in ("lose-session", "restore-session",
                        "expire-session"):
            self._session_action(action)
        elif action == "shard-kill":
            if self.shard_target is None:
                self.log.warning("chaos: shard-kill with no shard "
                                 "target; skipped")
            else:
                self.shard_target(int(kwargs.get("shard", -1)))
        elif action == "worker-roll":
            if self.roll_target is None:
                self.log.warning("chaos: worker-roll with no roll "
                                 "target; skipped")
            else:
                self.roll_target(int(kwargs.get("shard", -1)))
        elif action == "rrl-flood":
            self._flood_action(kwargs)
        elif action in STREAM_ACTIONS:
            self._stream_action(action, kwargs)
        elif action in VERIFY_ACTIONS:
            self._verify_action(action, kwargs)
        else:
            raise ValueError(f"unknown chaos action {action!r}")
        self.applied.append((time.monotonic(), action))
        if self.recorder is not None:
            self.recorder.record("chaos-inject", action=action, **{
                k: v for k, v in kwargs.items()})
        self.log.info("chaos: injected %s %s", action, kwargs or "")

    def _session_action(self, action: str) -> None:
        st = self.store
        if st is None:
            self.log.warning("chaos: %s with no store target; skipped",
                             action)
            return
        if action == "lose-session":
            # FakeStore.lose_session; the ZK test server's analog is
            # severing this member's connections without expiry
            fn = getattr(st, "lose_session", None) \
                or getattr(st, "drop_connections", None)
        elif action == "expire-session":
            fn = getattr(st, "expire_session", None)
        else:
            # restore: FakeStore.start_session; the real client
            # re-establishes on its own once connections are allowed
            fn = getattr(st, "start_session", None)
        if fn is None:
            self.log.warning("chaos: store %s has no hook for %s",
                             type(st).__name__, action)
            return
        fn()

    def _verify_action(self, action: str, kwargs: dict) -> None:
        vt = self.verify_target
        if vt is None:
            self.log.warning("chaos: %s with no verify target; skipped",
                             action)
            return
        fn = getattr(vt, action.replace("-", "_"), None)
        if fn is None:
            self.log.warning("chaos: verify target %s has no hook "
                             "for %s", type(vt).__name__, action)
            return
        result = fn(**kwargs)
        if result is None:
            # nothing to corrupt (empty table / no matching entry):
            # loud, so a smoke that asserted a detection can tell
            # "not injected" apart from "not detected"
            self.log.warning("chaos: %s found no target state", action)

    def _flood_action(self, kwargs: dict) -> None:
        """Spoofed-prefix UDP burst: n queries round-robined across
        sockets bound inside the attacker /24s, replies never read.
        Synchronous and send-only — a few hundred sendto()s finish in
        single-digit milliseconds, well inside timeline accuracy."""
        target = self.udp_target or self.tcp_target
        if target is None:
            self.log.warning("chaos: rrl-flood with no udp target; "
                             "skipped")
            return
        host, port, default_qname = target
        n = int(kwargs.get("n", 400))
        qname = str(kwargs.get("qname", default_qname))
        from binder_tpu.dns.wire import Type, make_query
        import socket as socket_mod
        socks = []
        for pfx in FLOOD_PREFIXES:
            for host_octet in (7, 8):
                s = socket_mod.socket(socket_mod.AF_INET,
                                      socket_mod.SOCK_DGRAM)
                try:
                    s.bind((f"{pfx}.{host_octet}", 0))
                    s.connect((host, port))
                    s.setblocking(False)
                except OSError:
                    s.close()
                    continue
                socks.append(s)
        if not socks:
            self.log.warning("chaos: rrl-flood could not bind any "
                             "spoofed source; skipped")
            return
        try:
            for i in range(n):
                wire = make_query(qname, Type.A,
                                  qid=(i % 65535) + 1).encode()
                try:
                    socks[i % len(socks)].send(wire)
                except OSError:
                    # full socket buffer / ICMP-refused connect errors
                    # are flood reality, not harness failures
                    pass
        finally:
            for s in socks:
                s.close()

    def _stream_action(self, action: str, kwargs: dict) -> None:
        if self.tcp_target is None:
            self.log.warning("chaos: %s with no tcp target; skipped",
                             action)
            return
        from binder_tpu.chaos.stream import run_stream_fault
        coro = run_stream_fault(action, *self.tcp_target, **kwargs)
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            # no loop (synchronous unit-test entry): drive inline
            asyncio.run(coro)
            return
        # fault clients do real socket I/O: run them as tasks so the
        # plan's timeline keeps its scripted instants
        task = asyncio.ensure_future(coro)
        self._stream_tasks.add(task)
        task.add_done_callback(self._stream_tasks.discard)

    async def stream_quiesce(self) -> None:
        """Await completion of every in-flight stream fault client
        (smokes assert table state after the faults, not during)."""
        while self._stream_tasks:
            await asyncio.gather(*list(self._stream_tasks),
                                 return_exceptions=True)

    # -- the scripted run --

    async def run(self) -> None:
        """Play the plan's timeline against the targets.  Sleeps are
        relative to the run's own start; actions land within event-loop
        scheduling accuracy of their scripted instants."""
        loop = asyncio.get_running_loop()
        self.started_mono = loop.time()
        for t, action, kwargs in self.plan.timeline:
            delay = self.started_mono + t - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            try:
                self.apply(action, kwargs)
            except Exception:  # noqa: BLE001 — keep injecting
                self.log.exception("chaos action %s failed", action)

    def start(self) -> "asyncio.Task":
        return asyncio.ensure_future(self.run())
