"""ChaosUpstream: a recursion upstream that misbehaves on command.

A minimal in-process DNS server (UDP + TCP) standing in for a
remote-DC binder, answering A/IN from a static name→address map —
except that every packet first consults a :class:`FaultPlan`'s live
``upstream`` fault state:

- ``dead``      — drop everything (the dead-peer shape breakers exist
                  for);
- ``loss``      — drop with probability p (lossy cross-DC link);
- ``delay_ms``  — hold the response (slow peer; what hedging beats);
- ``dup``       — send the response twice (duplicate-delivery paths);
- ``truncate``  — answer TC=1 with no answers over UDP, forcing the
                  client's TCP retry (TCP serves the real answer).

The response is built by patching the *request* wire — id and question
echoed byte-verbatim — so the chaos upstream is transparent to the
client's dns0x20 validation, exactly like a real binder peer.

Used by tests/test_chaos.py, ``tools/chaos_smoke.py``, and the bench's
degraded axis.
"""
from __future__ import annotations

import asyncio
import logging
import socket
import struct
from typing import Dict, Optional, Tuple

from binder_tpu.chaos.plan import FaultPlan


def _parse_question(data: bytes) -> Optional[Tuple[str, int, int]]:
    """(lowercased qname, qtype, question_end_offset) of a
    single-question query wire, or None when malformed."""
    if len(data) < 17 or data[4:6] != b"\x00\x01":
        return None
    labels = []
    off = 12
    try:
        while True:
            ll = data[off]
            if ll == 0:
                off += 1
                break
            if ll & 0xC0:
                return None
            labels.append(data[off + 1:off + 1 + ll])
            off += 1 + ll
        qtype = (data[off] << 8) | data[off + 1]
    except IndexError:
        return None
    if off + 4 > len(data):
        return None
    try:
        name = b".".join(labels).lower().decode("ascii")
    except UnicodeDecodeError:
        return None
    return name, qtype, off + 4


class ChaosUpstream:
    def __init__(self, plan: FaultPlan,
                 hosts: Optional[Dict[str, str]] = None,
                 ttl: int = 30,
                 log: Optional[logging.Logger] = None) -> None:
        self.plan = plan
        self.hosts = dict(hosts or {})
        self.ttl = ttl
        self.log = log or logging.getLogger("binder.chaos.upstream")
        self.port: Optional[int] = None
        self._udp_transport = None
        self._tcp_server: Optional[asyncio.AbstractServer] = None
        # per-fault accounting the soak report reads back
        self.served = 0
        self.dropped = 0
        self.delayed = 0
        self.duplicated = 0
        self.truncated = 0

    # -- answer assembly (request-wire patching, lane-style) --

    def build_response(self, data: bytes, tc: bool) -> Optional[bytes]:
        parsed = _parse_question(data)
        if parsed is None:
            return None
        name, qtype, q_end = parsed
        rd = data[2] & 0x01
        addr = self.hosts.get(name) if qtype == 1 else None
        body = b""
        ancount = 0
        rcode = 0
        if tc:
            pass                        # TC=1, empty answer section
        elif addr is not None:
            try:
                packed = socket.inet_aton(addr)
            except OSError:
                return None
            body = (b"\xc0\x0c\x00\x01\x00\x01"
                    + struct.pack(">IH", self.ttl, 4) + packed)
            ancount = 1
        else:
            rcode = 3                   # NXDOMAIN for unmapped names
        flags = 0x8400 | (0x0100 if rd else 0) | (0x0200 if tc else 0) \
            | rcode
        return (data[:2] + struct.pack(">HHHHH", flags, 1, ancount, 0, 0)
                + data[12:q_end] + body)

    # -- UDP (the faulted path) --

    class _Proto(asyncio.DatagramProtocol):
        def __init__(self, owner: "ChaosUpstream") -> None:
            self.owner = owner
            self.transport = None

        def connection_made(self, transport) -> None:
            self.transport = transport

        def datagram_received(self, data: bytes, addr) -> None:
            owner = self.owner
            faults = owner.plan.upstream
            rng = owner.plan.rng
            if faults.dead or (faults.loss > 0.0
                               and rng.random() < faults.loss):
                owner.dropped += 1
                return
            resp = owner.build_response(data, tc=faults.truncate)
            if resp is None:
                return
            if faults.truncate:
                owner.truncated += 1
            copies = 1
            if faults.dup > 0.0 and rng.random() < faults.dup:
                owner.duplicated += 1
                copies = 2

            def send() -> None:
                if self.transport is None or self.transport.is_closing():
                    return
                for _ in range(copies):
                    self.transport.sendto(resp, addr)
                owner.served += 1

            if faults.delay_ms > 0.0:
                owner.delayed += 1
                asyncio.get_running_loop().call_later(
                    faults.delay_ms / 1000.0, send)
            else:
                send()

    # -- TCP (the truncation-retry path; faults apply to loss/dead) --

    async def _tcp_conn(self, reader: asyncio.StreamReader,
                        writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                hdr = await reader.readexactly(2)
                n = int.from_bytes(hdr, "big")
                data = await reader.readexactly(n)
                faults = self.plan.upstream
                if faults.dead or (faults.loss > 0.0
                                   and self.plan.rng.random()
                                   < faults.loss):
                    self.dropped += 1
                    continue
                resp = self.build_response(data, tc=False)
                if resp is None:
                    continue
                if faults.delay_ms > 0.0:
                    await asyncio.sleep(faults.delay_ms / 1000.0)
                writer.write(len(resp).to_bytes(2, "big") + resp)
                await writer.drain()
                self.served += 1
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, OSError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass

    # -- lifecycle --

    async def start(self, address: str = "127.0.0.1",
                    port: int = 0) -> int:
        loop = asyncio.get_running_loop()
        self._udp_transport, _ = await loop.create_datagram_endpoint(
            lambda: self._Proto(self), local_addr=(address, port))
        self.port = self._udp_transport.get_extra_info("sockname")[1]
        # TCP shares the UDP port number (binder peers serve both)
        self._tcp_server = await asyncio.start_server(
            self._tcp_conn, address, self.port)
        return self.port

    async def stop(self) -> None:
        if self._udp_transport is not None:
            self._udp_transport.close()
            self._udp_transport = None
        if self._tcp_server is not None:
            self._tcp_server.close()
            await self._tcp_server.wait_closed()
            self._tcp_server = None

    def stats(self) -> dict:
        return {"served": self.served, "dropped": self.dropped,
                "delayed": self.delayed, "duplicated": self.duplicated,
                "truncated": self.truncated,
                "faults": self.plan.upstream.snapshot()}
