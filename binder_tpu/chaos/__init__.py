"""Fault-injection (chaos) layer: prove behavior under failure.

- :class:`FaultPlan` — scriptable fault schedule (DSL or builder) +
  live upstream fault state;
- :class:`ChaosDriver` — plays a plan against a store (fake or ZK test
  server), a churn mutator, and the event loop;
- :class:`ChaosUpstream` — a recursion upstream applying the plan's
  packet-level faults (loss / delay / duplication / truncation /
  dead-peer).

Consumed by tests/test_chaos.py, ``tools/chaos_smoke.py`` (the
``make chaos-smoke`` target), the bench's degraded axis, and — via the
``chaos`` config block — a live server under test (``main.py``).
"""
from binder_tpu.chaos.plan import ChaosDriver, FaultPlan, UpstreamFaults
from binder_tpu.chaos.upstream import ChaosUpstream

__all__ = ["ChaosDriver", "FaultPlan", "UpstreamFaults", "ChaosUpstream"]
