"""Watch-driven in-memory mirror of the coordination-store tree.

Port of the reference's ZKCache/TreeNode (``lib/zk.js:20-228``): one node
per domain label, eagerly mirroring the whole subtree under the DNS domain
so the query path never touches the store (SURVEY §3.5 — "what makes §3.2
I/O-free").

Key behaviors preserved:
- ``domain_to_path``: ``a.foo.com → /com/foo/a`` (``lib/zk.js:225-228``).
- One watcher per znode; children diffs keep existing nodes, create+bind
  added ones, unbind removed subtrees (``lib/zk.js:120-138``).
- Full tree rebind on every session event (``lib/zk.js:45-47,68-76``);
  ``is_ready()`` is false only until the first session.
- Unparseable or non-object znode JSON is ignored, keeping prior data
  (``lib/zk.js:139-154``).
- Host-like record types maintain the IP → node reverse map for PTR
  (``lib/zk.js:172-193``).

Deliberate deviations (stale-reverse-entry hazards the reference survey
flags in §7.3; both strictly reduce wrong answers):
- The reverse map only drops an IP entry if it still points at the node
  being updated (the reference deletes unconditionally, clobbering an entry
  another node may now own, ``lib/zk.js:184-185``).
- ``unbind`` also removes the node's reverse entry; the reference leaks it,
  so PTR queries could resolve to hosts that left the tree
  (``lib/zk.js:195-208`` never touches ca_revLookup).
"""
from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional

from binder_tpu.store.interface import StoreClient, Watcher

# Record types that represent a single addressable host: these maintain the
# reverse (PTR) map and are the types a service's children may carry.
# Reference ``lib/zk.js:172-179``.
HOST_TYPES = frozenset({
    "db_host", "host", "load_balancer", "moray_host",
    "redis_host", "ops_host", "rr_host",
})


def domain_to_path(domain: str) -> str:
    assert domain
    return "/" + "/".join(reversed(domain.split(".")))


def _rev_name(ip: Optional[str]) -> Optional[str]:
    """'10.1.2.3' -> '3.2.1.10.in-addr.arpa' (the PTR qname an answer
    for this address is cached under); None for non-IPv4 strings —
    reverse resolution is IPv4-only (engine.resolve_ptr, matching the
    reference lib/server.js:71-84).  No canonicalization: the engine
    does not validate octets either, so a non-canonical stored address
    ('10.1.2.03') pairs with exactly the reverse qname a client would
    use to reach it."""
    if not ip:
        return None
    parts = ip.split(".")
    if len(parts) != 4 or not all(p.isdigit() for p in parts):
        return None
    return ".".join(reversed(parts)) + ".in-addr.arpa"


class TreeNode:
    """One mirrored znode == one domain label (reference TreeNode)."""

    __slots__ = ("name", "domain", "path", "cache", "kids", "data", "ip",
                 "watcher", "log")

    def __init__(self, cache: "MirrorCache", parent_domain: str,
                 name: str) -> None:
        self.name = name
        domain = name if not parent_domain else name + "." + parent_domain
        self.domain = domain.lower()
        self.path = domain_to_path(self.domain)
        self.cache = cache
        self.kids: Dict[str, TreeNode] = {}
        self.data = None
        self.ip: Optional[str] = None
        self.watcher: Optional[Watcher] = None
        self.log = cache.log
        cache.nodes[self.domain] = self

    @property
    def children(self) -> List["TreeNode"]:
        return list(self.kids.values())

    # -- watch event handlers --

    def on_children_changed(self, kids: List[str]) -> None:
        self.cache.bump_gen()
        if self.cache.m_watch_children is not None:
            self.cache.m_watch_children.inc()
        # answers that may change: this node's own (service answer sets
        # derive from children) and each newly appearing child's name
        # (a cached REFUSED for it is now wrong); removed subtrees emit
        # their own tags from unbind()
        tags = {self.domain}
        new_kids: Dict[str, TreeNode] = {}
        for kid in kids:
            existing = self.kids.pop(kid, None)
            if existing is not None:
                new_kids[kid] = existing
            else:
                node = TreeNode(self.cache, self.domain, kid)
                new_kids[kid] = node
                tags.add(node.domain)
                node.rebind()
        for removed in list(self.kids.values()):
            removed.unbind()
        self.kids = new_kids
        self.cache.invalidate(tags)

    def on_data_changed(self, data: bytes) -> None:
        self.cache.bump_gen()
        if self.cache.m_watch_data is not None:
            self.cache.m_watch_data.inc()
        try:
            parsed = json.loads(data.decode("utf-8")) if data else None
        except (ValueError, UnicodeDecodeError) as e:
            self.log.warning("ignoring node %s: failed to parse data: %s",
                             self.path, e)
            if self.cache.m_parse_failures is not None:
                self.cache.m_parse_failures.inc()
            return                      # old data kept: answers unchanged
        # JS typeof-object check admits dicts, lists, and null
        # (lib/zk.js:149-154); anything else is ignored, keeping old data.
        if parsed is not None and not isinstance(parsed, (dict, list)):
            self.log.warning("ignoring node %s: parsed JSON is not an object",
                             self.path)
            return
        old_ip = self.ip
        self.data = parsed

        rtype = parsed.get("type") if isinstance(parsed, dict) else None
        if not isinstance(rtype, str) or rtype not in HOST_TYPES:
            # no longer (or never was) a host-like record: drop any reverse
            # entry we own so PTR can't serve a stale mapping
            self._drop_rev_entry()
        else:
            record = parsed.get(rtype)
            if not isinstance(record, dict):
                self._drop_rev_entry()
            else:
                addr = record.get("address")
                self._drop_rev_entry()
                self.ip = addr
                if addr:
                    self.cache.rev_lookup[addr] = self

        # answers that may change: this name, the parent's (service
        # answer sets embed child data), and PTR answers for the old and
        # new address
        tags = {self.domain}
        if "." in self.domain:
            tags.add(self.domain.split(".", 1)[1])
        for rev in (_rev_name(old_ip), _rev_name(self.ip)):
            if rev is not None:
                tags.add(rev)
        self.cache.invalidate(tags)

    def _drop_rev_entry(self) -> None:
        if self.ip and self.cache.rev_lookup.get(self.ip) is self:
            del self.cache.rev_lookup[self.ip]
        self.ip = None

    # -- lifecycle --

    def rebind(self) -> None:
        """(Re-)register watchers for this subtree (lib/zk.js:209-223).

        Kids that exist *before* re-registering need explicit rebinds; kids
        created during the (possibly synchronous) initial children delivery
        were already bound by on_children_changed and must not be rebound
        again — with a synchronous store that would compound to 2^depth
        redundant rebinds per session event.
        """
        existing = list(self.kids.values())
        if self.watcher is not None:
            self.watcher.clear()
        self.watcher = self.cache.store.watcher(self.path)
        self.watcher.on("children", self.on_children_changed)
        self.watcher.on("data", self.on_data_changed)
        for kid in existing:
            if self.kids.get(kid.name) is kid:
                kid.rebind()

    def unbind(self) -> None:
        self.cache.bump_gen()
        self.log.debug("unbinding node at %s", self.path)
        if self.watcher is not None:
            self.watcher.clear()
        for kid in list(self.kids.values()):
            kid.unbind()
        if self.cache.nodes.get(self.domain) is self:
            del self.cache.nodes[self.domain]
        tags = {self.domain}
        if "." in self.domain:
            tags.add(self.domain.split(".", 1)[1])
        rev = _rev_name(self.ip)
        if rev is not None:
            tags.add(rev)
        if self.ip and self.cache.rev_lookup.get(self.ip) is self:
            del self.cache.rev_lookup[self.ip]
        self.cache.invalidate(tags)


class MirrorCache:
    """The ZKCache equivalent: domain-keyed node index + reverse-IP index."""

    #: watch events within one STORM_WINDOW that flag a watch storm
    #: (a registrar gone wild or an ensemble replaying a large backlog —
    #: either way the mirror is churning far above steady state and the
    #: flight recorder should keep the evidence)
    STORM_THRESHOLD = 500
    STORM_WINDOW = 1.0

    def __init__(self, store: StoreClient, domain: str,
                 log: Optional[logging.Logger] = None,
                 collector=None, recorder=None) -> None:
        self.store = store
        self.domain = domain.lower()
        self.log = log or logging.getLogger("binder.cache")
        self.recorder = recorder
        self.nodes: Dict[str, TreeNode] = {}
        self.rev_lookup: Dict[str, TreeNode] = {}
        # staleness instrumentation: monotonic instants of the last
        # applied mutation and the last full rebuild.  While the store
        # session is down no watch events arrive, so the mutation age
        # IS the mirror's staleness bound — the quantity the status
        # endpoint and binder_mirror_staleness_seconds report.
        self.last_mutation_mono: Optional[float] = None
        self.last_rebuild_mono: Optional[float] = None
        # watch-storm window accounting
        self._storm_window_start = 0.0
        self._storm_count = 0
        self._storm_flagged = False
        # generation counter: bumped on every mirrored mutation; drives
        # the balancer's generation broadcast (its cache entries are
        # validated against the backend's advertised gen)
        self.gen = 0
        # epoch: bumped only on full rebuilds (session events), where
        # arbitrary unseen changes may stream in — the in-process answer
        # caches key their entries on this and rely on per-name
        # invalidation (below) for ordinary mutations, so one churning
        # record no longer evicts every cached answer
        self.epoch = 0
        # mutation subscribers (e.g. the balancer generation broadcast);
        # called synchronously on every bump — keep them cheap
        self._mutation_cbs: List = []
        # per-name invalidation subscribers: called with a set of
        # dependency tags (lookup domains / PTR qnames) whose answers a
        # mutation may have changed
        self._invalidate_cbs: List = []
        # store-mirror observability (the reference gets the analogous
        # client metrics by passing its artedi collector into zkstream,
        # lib/zk.js:26-38); all optional — tests build bare caches
        self.m_watch_children = self.m_watch_data = None
        self.m_parse_failures = self.m_rebuilds = None
        if collector is not None:
            self.m_watch_children = collector.counter(
                "binder_store_watch_events",
                "store watch events applied to the mirror").labelled(
                    {"kind": "children"})
            self.m_watch_data = collector.counter(
                "binder_store_watch_events", "").labelled({"kind": "data"})
            self.m_parse_failures = collector.counter(
                "binder_store_node_parse_failures",
                "znodes whose JSON could not be applied").labelled()
            self.m_rebuilds = collector.counter(
                "binder_store_session_rebuilds",
                "full mirror rebuilds triggered by store session events"
            ).labelled()
            collector.gauge(
                "binder_store_mirrored_nodes",
                "domain nodes currently mirrored from the store"
            ).set_function(lambda: len(self.nodes))
            collector.gauge(
                "binder_store_reverse_entries",
                "IP addresses in the PTR reverse index"
            ).set_function(lambda: len(self.rev_lookup))
            collector.gauge(
                "binder_store_generation",
                "mirror mutation generation counter"
            ).set_function(lambda: self.gen)
            collector.gauge(
                "binder_store_ready",
                "1 when the mirror has a live session and root node"
            ).set_function(lambda: 1.0 if self.is_ready() else 0.0)
            collector.gauge(
                "binder_mirror_staleness_seconds",
                "age of the last change applied to the store mirror "
                "(bounds answer staleness while the session is down)"
            ).set_function(lambda: self.staleness_seconds() or 0.0)
        store.on_session(self.rebuild)

    def on_mutation(self, cb) -> None:
        """Subscribe to generation bumps (any mirrored store mutation)."""
        self._mutation_cbs.append(cb)

    def on_invalidate(self, cb) -> None:
        """Subscribe to per-name invalidation: cb(tags) where tags is a
        set of lookup domains / PTR qnames whose answers may have
        changed (see TreeNode's watch handlers)."""
        self._invalidate_cbs.append(cb)

    def invalidate(self, tags) -> None:
        if not tags:
            return
        for cb in self._invalidate_cbs:
            try:
                cb(tags)
            except Exception:  # noqa: BLE001 — a subscriber bug must
                self.log.exception("invalidate callback failed")  # not stop serving

    def bump_gen(self) -> None:
        self.gen += 1
        now = time.monotonic()
        self.last_mutation_mono = now
        if self.recorder is not None:
            # watch-storm detection: count mutations per fixed window,
            # flag once per window when the threshold is crossed
            if now - self._storm_window_start > self.STORM_WINDOW:
                self._storm_window_start = now
                self._storm_count = 0
                self._storm_flagged = False
            self._storm_count += 1
            if (self._storm_count >= self.STORM_THRESHOLD
                    and not self._storm_flagged):
                self._storm_flagged = True
                self.recorder.record(
                    "watch-storm", events=self._storm_count,
                    window_s=self.STORM_WINDOW, generation=self.gen)
        for cb in self._mutation_cbs:
            try:
                cb()
            except Exception:  # noqa: BLE001 — a subscriber bug must not
                self.log.exception("mutation callback failed")  # stop serving

    def is_ready(self) -> bool:
        return self.domain in self.nodes

    def staleness_seconds(self) -> Optional[float]:
        """Age of the last applied change (mutation or full rebuild).

        While the store session is live this is ordinary quiet time;
        with the session down it bounds how old the mirror's answers
        may be — the "silent aging" quantity a pure query-side view
        cannot see.  None when nothing was ever mirrored."""
        last = self.last_mutation_mono
        if last is None or (self.last_rebuild_mono is not None
                            and self.last_rebuild_mono > last):
            last = self.last_rebuild_mono
        if last is None:
            return None
        return time.monotonic() - last

    def lookup(self, domain: str) -> Optional[TreeNode]:
        return self.nodes.get(domain)

    def reverse_lookup(self, ip: str) -> Optional[TreeNode]:
        return self.rev_lookup.get(ip)

    # -- traced entry points (per-stage attribution) --
    #
    # The resolver hands its QueryCtx in so the mirror probe gets its
    # own phase stamp ("store-lookup") on the query's attribution
    # timeline; the lookup itself is identical.  Kept as separate
    # methods so non-query callers (zone refresh, tests) pay nothing.

    def invalidate_all(self, reason: str = "") -> None:
        """Epoch bump OUTSIDE a rebuild: every answer cached anywhere
        (Python answer cache, compiled table, native C caches, the
        balancer) must revalidate.  Used by the degradation policy at
        state transitions — an answer rendered under one staleness mode
        must never be served under another (e.g. a fresh-rendered wire
        into exhaustion, or an unclamped TTL while stale-serving).

        Deliberately does NOT touch the staleness timestamps: the
        mirror's data did not change, only its permissibility — the
        staleness clock must keep aging."""
        self.epoch += 1
        if self.recorder is not None:
            self.recorder.record("cache-flush", reason=reason,
                                 epoch=self.epoch)
        for cb in self._mutation_cbs:
            try:
                cb()
            except Exception:  # noqa: BLE001 — a subscriber bug must
                self.log.exception("mutation callback failed")  # not stop serving

    def lookup_traced(self, domain: str, query) -> Optional[TreeNode]:
        node = self.nodes.get(domain)
        query.stamp("store-lookup")
        return node

    def reverse_lookup_traced(self, ip: str, query) -> Optional[TreeNode]:
        node = self.rev_lookup.get(ip)
        query.stamp("store-lookup")
        return node

    def rebuild(self) -> None:
        """Re-mirror from scratch-or-current on (re)session
        (lib/zk.js:68-76)."""
        if self.m_rebuilds is not None:
            self.m_rebuilds.inc()
        self.last_rebuild_mono = time.monotonic()
        if self.recorder is not None:
            self.recorder.record("mirror-rebuild", epoch=self.epoch + 1,
                                 nodes=len(self.nodes))
        # a (re)session may deliver arbitrary unseen changes while the
        # subtree re-syncs: conservatively invalidate every cached answer
        self.epoch += 1
        tn = self.nodes.get(self.domain)
        if tn is None:
            parts = self.domain.split(".")
            tn = TreeNode(self, ".".join(parts[1:]), parts[0])
        tn.rebind()

    def stop(self) -> None:
        self.store.close()
