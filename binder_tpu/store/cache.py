"""Watch-driven in-memory mirror of the coordination-store tree.

Port of the reference's ZKCache/TreeNode (``lib/zk.js:20-228``): one node
per domain label, eagerly mirroring the whole subtree under the DNS domain
so the query path never touches the store (SURVEY §3.5 — "what makes §3.2
I/O-free").

Key behaviors preserved:
- ``domain_to_path``: ``a.foo.com → /com/foo/a`` (``lib/zk.js:225-228``).
- One watcher per znode; children diffs keep existing nodes, create+bind
  added ones, unbind removed subtrees (``lib/zk.js:120-138``).
- Full tree rebind on every session event (``lib/zk.js:45-47,68-76``);
  ``is_ready()`` is false only until the first session.
- Unparseable or non-object znode JSON is ignored, keeping prior data
  (``lib/zk.js:139-154``).
- Host-like record types maintain the IP → node reverse map for PTR
  (``lib/zk.js:172-193``).

Deliberate deviations (stale-reverse-entry hazards the reference survey
flags in §7.3; both strictly reduce wrong answers):
- The reverse map only drops an IP entry if it still points at the node
  being updated (the reference deletes unconditionally, clobbering an entry
  another node may now own, ``lib/zk.js:184-185``).
- ``unbind`` also removes the node's reverse entry; the reference leaks it,
  so PTR queries could resolve to hosts that left the tree
  (``lib/zk.js:195-208`` never touches ca_revLookup).

Production-zone-scale representation (ISSUE 7): nodes store COMPACT
records (``store/names.py`` — host-likes as 4-tuples, everything else
with interned keys) and interned domain strings; ``data`` is a property
that expands on demand so every consumer keeps reading parsed-JSON
shapes, while hot paths read ``TreeNode.rec`` directly.  The
session-event full rebuild is CHUNKED across event-loop passes
(time-budgeted) so a million-name re-mirror never stalls serving or
trips the loop-lag watchdog — the mirror keeps answering from its
existing nodes while the walk re-registers watchers underneath it.
"""
from __future__ import annotations

import asyncio
import ipaddress
import json
import logging
import time
from collections import deque
from typing import Dict, List, Optional

from binder_tpu.store import names as _names
from binder_tpu.store.interface import StoreClient

# Record types that represent a single addressable host: these maintain the
# reverse (PTR) map and are the types a service's children may carry
# (reference ``lib/zk.js:172-179``) — and exactly the types the compact
# tuple representation covers (the canonical set lives in store/names.py).
HOST_TYPES = _names.HOST_TYPES


def domain_to_path(domain: str) -> str:
    assert domain
    return "/" + "/".join(reversed(domain.split(".")))


def _rev_name(ip: Optional[str]) -> Optional[str]:
    """'10.1.2.3' -> '3.2.1.10.in-addr.arpa', '2001:db8::1' ->
    '...ip6.arpa' (the PTR qname an answer for this address is cached
    under); None for strings that are neither.  For IPv4, no
    canonicalization: the engine does not validate octets either, so a
    non-canonical stored address ('10.1.2.03') pairs with exactly the
    reverse qname a client would use to reach it.  IPv6 addresses are
    canonical by the time they reach here (``TreeNode.ip`` normalizes),
    matching ``wire.ip_from_reverse_name``'s canonical output."""
    if not ip:
        return None
    if ":" in ip:
        try:
            return ipaddress.IPv6Address(ip).reverse_pointer
        except (ValueError, ipaddress.AddressValueError):
            return None
    parts = ip.split(".")
    if len(parts) != 4 or not all(p.isdigit() for p in parts):
        return None
    return ".".join(reversed(parts)) + ".in-addr.arpa"


class TreeNode:
    """One mirrored znode == one domain label (reference TreeNode).

    Memory layout is the point at zone scale: six slots, the domain
    interned, ``kids`` allocated only for interior nodes (None for the
    million leaves), the record compact (``names.compact_record``), and
    ``name``/``path``/``data`` derived on demand instead of stored."""

    __slots__ = ("domain", "cache", "kids", "_rec")

    def __init__(self, cache: "MirrorCache", parent_domain: str,
                 name: str) -> None:
        domain = name if not parent_domain else name + "." + parent_domain
        # NOT pool-interned: each mirrored domain is unique, so the
        # nodes index itself is its canonical home (MirrorCache.canon);
        # pooling a million one-off strings would cost a pool entry per
        # name for zero dedup
        self.domain = domain.lower()
        self.cache = cache
        # labels of current children (a tuple, not a dict of nodes:
        # children resolve through the cache's node index on demand);
        # None for the leaf-heavy common case
        self.kids: Optional[tuple] = None
        self._rec = None
        cache.nodes[self.domain] = self

    @property
    def name(self) -> str:
        return self.domain.split(".", 1)[0]

    @property
    def path(self) -> str:
        return domain_to_path(self.domain)

    @property
    def log(self) -> logging.Logger:
        return self.cache.log

    @property
    def rec(self):
        """The stored record in its COMPACT form: a
        ``names.CompactRec`` tuple for host-like single-address
        records, else the parsed JSON shape.  The hot paths' accessor —
        no per-read allocation."""
        return self._rec

    @property
    def data(self):
        """The record as parsed JSON (dict/list/None) — expanded on
        demand from the compact form.  Equal (``==``) to what
        ``json.loads`` produced; identity is not preserved."""
        return _names.expand_record(self._rec)

    @property
    def ip(self) -> Optional[str]:
        """The address this node's record binds in the reverse map —
        derived from the record (was a stored slot; at a million
        names every slot counts)."""
        rec = self._rec
        addr = None
        if type(rec) is tuple:
            addr = rec[1] if rec[0] in HOST_TYPES else None
        elif isinstance(rec, dict):
            rtype = rec.get("type")
            if isinstance(rtype, str) and rtype in HOST_TYPES:
                sub = rec.get(rtype)
                if isinstance(sub, dict):
                    addr = sub.get("address")
        if addr and ":" in addr:
            # IPv6: the reverse map is keyed by canonical form so a
            # stored "2001:DB8:0::1" meets the canonical string
            # ip_from_reverse_name derives from an ip6.arpa qname
            try:
                return str(ipaddress.IPv6Address(addr))
            except (ValueError, ipaddress.AddressValueError):
                return None
        return addr

    def _kid_node(self, label: str) -> Optional["TreeNode"]:
        return self.cache.nodes.get((label + "." + self.domain).lower())

    @property
    def children(self) -> List["TreeNode"]:
        if not self.kids:
            return []
        out = []
        for label in self.kids:
            node = self._kid_node(label)
            if node is not None:
                out.append(node)
        return out

    # -- watch event handlers --

    def on_children_changed(self, kids: List[str]) -> None:
        cache = self.cache
        cache.bump_gen()
        if cache.m_watch_children is not None:
            cache.m_watch_children.inc()
        # answers that may change: this node's own (service answer sets
        # derive from children) and each newly appearing child's name
        # (a cached REFUSED for it is now wrong); removed subtrees emit
        # their own tags from unbind()
        tags = {self.domain}
        gone = set(self.kids or ())
        changed = False
        for kid in kids:
            if kid in gone:
                gone.discard(kid)       # survives: node stays as-is
            else:
                changed = True
                node = TreeNode(cache, self.domain, kid)
                tags.add(node.domain)
                node.rebind()
        self.kids = tuple(kids) or None
        for label in gone:
            changed = True
            removed = cache.nodes.get((label + "." + self.domain).lower())
            if removed is not None:
                removed.unbind()
        if changed:
            cache.invalidate(tags)
        # unchanged child set (every re-delivery during a session
        # rebuild walk): answers cannot have changed, so no
        # invalidation work — at a million names, per-node invalidation
        # during a re-mirror was the dominant rebuild cost (each event
        # walks the native cache table)

    def on_data_changed(self, data: bytes) -> None:
        cache = self.cache
        cache.bump_gen()
        if cache.m_watch_data is not None:
            cache.m_watch_data.inc()
        try:
            parsed = json.loads(data.decode("utf-8")) if data else None
        except (ValueError, UnicodeDecodeError) as e:
            self.log.warning("ignoring node %s: failed to parse data: %s",
                             self.path, e)
            if cache.m_parse_failures is not None:
                cache.m_parse_failures.inc()
            return                      # old data kept: answers unchanged
        # JS typeof-object check admits dicts, lists, and null
        # (lib/zk.js:149-154); anything else is ignored, keeping old data.
        if parsed is not None and not isinstance(parsed, (dict, list)):
            self.log.warning("ignoring node %s: parsed JSON is not an object",
                             self.path)
            return
        # reverse-map upkeep around the record swap: drop the entry we
        # own under the OLD address (never another node's — the
        # collision guard), install under the new one.  A record that
        # is no longer host-like simply yields ip None, so its entry
        # drops and PTR can't serve a stale mapping.  The unchanged
        # case (same address, entry already ours — every re-delivery
        # during a session rebuild) must NOT del+reinsert: a million
        # same-key delete/insert cycles force periodic O(zone) dict
        # compactions, which is exactly the loop stall the chunked
        # rebuild exists to avoid.
        rec = _names.compact_record(parsed)
        if rec == self._rec:
            # identical record re-delivered — the shape of EVERY data
            # event a session-rebuild walk fires: answers cannot have
            # changed, so skip the invalidation fan-out entirely (the
            # rebuild's epoch bump already revalidates every cached
            # lane; per-name invalidation here was the dominant
            # re-mirror cost at zone scale, one native-table walk per
            # event).  The OLD object is kept on purpose: replacing a
            # zone's worth of (gc-frozen) records with equal copies
            # seeds gen-2 with survivors, and the eventual collection
            # is a ~400 ms serving stall.
            return
        old_ip = self.ip
        self._rec = rec
        new_ip = self.ip
        rev = cache.rev_lookup
        if new_ip != old_ip:
            if old_ip and rev.get(old_ip) is self:
                del rev[old_ip]
            if new_ip:
                rev[new_ip] = self
        elif new_ip and rev.get(new_ip) is not self:
            rev[new_ip] = self          # re-claim a colliding entry

        # answers that may change: this name, the parent's (service
        # answer sets embed child data), and PTR answers for the old and
        # new address
        tags = {self.domain}
        if "." in self.domain:
            tags.add(self.domain.split(".", 1)[1])
        for rev in (_rev_name(old_ip), _rev_name(self.ip)):
            if rev is not None:
                tags.add(rev)
        cache.invalidate(tags)

    # -- lifecycle --

    def rebind(self) -> None:
        """(Re-)register watchers for this subtree (lib/zk.js:209-223).

        Kids that exist *before* re-registering need explicit rebinds; kids
        created during the (possibly synchronous) initial children delivery
        were already bound by on_children_changed and must not be rebound
        again — with a synchronous store that would compound to 2^depth
        redundant rebinds per session event.
        """
        existing = self.children
        self.cache.store.bind_node(self.path, self)
        for kid in existing:
            if self.cache.nodes.get(kid.domain) is kid:
                kid.rebind()

    def rebind_shallow(self, queue: deque) -> None:
        """One node's share of a CHUNKED session rebuild: re-register
        this node's watcher (new kids discovered by the resulting
        children diff still bind recursively — they are new content the
        mirror must pick up whole), then defer the surviving existing
        kids onto the walk queue instead of recursing."""
        existing = self.children
        self.cache.store.bind_node(self.path, self)
        for kid in existing:
            if self.cache.nodes.get(kid.domain) is kid:
                queue.append(kid)

    def unbind(self) -> None:
        self.cache.bump_gen()
        self.log.debug("unbinding node at %s", self.path)
        self.cache.store.unbind_node(self.path, self)
        for kid in self.children:
            kid.unbind()
        if self.cache.nodes.get(self.domain) is self:
            del self.cache.nodes[self.domain]
        tags = {self.domain}
        if "." in self.domain:
            tags.add(self.domain.split(".", 1)[1])
        rev = _rev_name(self.ip)
        if rev is not None:
            tags.add(rev)
        if self.ip and self.cache.rev_lookup.get(self.ip) is self:
            del self.cache.rev_lookup[self.ip]
        self.cache.invalidate(tags)


class MirrorCache:
    """The ZKCache equivalent: domain-keyed node index + reverse-IP index."""

    #: watch events within one STORM_WINDOW that flag a watch storm
    #: (a registrar gone wild or an ensemble replaying a large backlog —
    #: either way the mirror is churning far above steady state and the
    #: flight recorder should keep the evidence)
    STORM_THRESHOLD = 500
    STORM_WINDOW = 1.0

    #: chunked-rebuild pacing: one drain pass re-registers at least
    #: REBUILD_MIN_CHUNK nodes and keeps going until the time budget is
    #: spent, then yields the loop to serving.  The budget is checked
    #: EVERY node past the floor — a node's rebind cost varies by three
    #: orders of magnitude (leaf vs a parent with a thousand children),
    #: so a count-based batch would stall the loop on parent-dense
    #: stretches.  2 ms per pass keeps a million-name rebuild far under
    #: the loop-lag watchdog's 250 ms stall threshold while still
    #: converging in seconds.
    REBUILD_BUDGET_S = 0.002
    REBUILD_MIN_CHUNK = 1

    def __init__(self, store: StoreClient, domain: str,
                 log: Optional[logging.Logger] = None,
                 collector=None, recorder=None) -> None:
        self.store = store
        self.domain = _names.intern_name(domain.lower())
        self.log = log or logging.getLogger("binder.cache")
        self.recorder = recorder
        self.pool = _names.POOL
        self.nodes: Dict[str, TreeNode] = {}
        self.rev_lookup: Dict[str, TreeNode] = {}
        # offer the node index as the store's direct event routing
        # table (fake store / shard replica feed route synchronously
        # through it; the ZooKeeper client uses it for watch-event
        # dispatch and shared, batched wire watches)
        getattr(store, "bind_source", lambda nodes: False)(self.nodes)
        # staleness instrumentation: monotonic instants of the last
        # applied mutation and the last full rebuild.  While the store
        # session is down no watch events arrive, so the mutation age
        # IS the mirror's staleness bound — the quantity the status
        # endpoint and binder_mirror_staleness_seconds report.
        self.last_mutation_mono: Optional[float] = None
        self.last_rebuild_mono: Optional[float] = None
        # watch-storm window accounting
        self._storm_window_start = 0.0
        self._storm_count = 0
        self._storm_flagged = False
        # generation counter: bumped on every mirrored mutation; drives
        # the balancer's generation broadcast (its cache entries are
        # validated against the backend's advertised gen)
        self.gen = 0
        # epoch: bumped only on full rebuilds (session events), where
        # arbitrary unseen changes may stream in — the in-process answer
        # caches key their entries on this and rely on per-name
        # invalidation (below) for ordinary mutations, so one churning
        # record no longer evicts every cached answer
        self.epoch = 0
        # chunked-rebuild state: the walk queue (None when no rebuild
        # is in flight), a generation guard so a session churning
        # mid-rebuild restarts the walk instead of interleaving two,
        # and the introspection counters the zone-scale bench reads
        self._rebuild_queue: Optional[deque] = None
        self._rebuild_gen = 0
        self._rebuild_started: Optional[float] = None
        self.rebuild_chunks = 0
        self.last_rebuild_duration_s: Optional[float] = None
        # mutation subscribers (e.g. the balancer generation broadcast);
        # called synchronously on every bump — keep them cheap
        self._mutation_cbs: List = []
        # per-name invalidation subscribers: called with a set of
        # dependency tags (lookup domains / PTR qnames) whose answers a
        # mutation may have changed
        self._invalidate_cbs: List = []
        # optional propagation tracer (binder_tpu/verify): bump_gen
        # opens each mutation's trace context, invalidate marks the
        # mirror-apply stage — both no-ops when unset
        self.tracer = None
        # store-mirror observability (the reference gets the analogous
        # client metrics by passing its artedi collector into zkstream,
        # lib/zk.js:26-38); all optional — tests build bare caches
        self.m_watch_children = self.m_watch_data = None
        self.m_parse_failures = self.m_rebuilds = None
        self._m_rebuild_chunks = None
        if collector is not None:
            self.m_watch_children = collector.counter(
                "binder_store_watch_events",
                "store watch events applied to the mirror").labelled(
                    {"kind": "children"})
            self.m_watch_data = collector.counter(
                "binder_store_watch_events", "").labelled({"kind": "data"})
            self.m_parse_failures = collector.counter(
                "binder_store_node_parse_failures",
                "znodes whose JSON could not be applied").labelled()
            self.m_rebuilds = collector.counter(
                "binder_store_session_rebuilds",
                "full mirror rebuilds triggered by store session events"
            ).labelled()
            collector.gauge(
                "binder_store_mirrored_nodes",
                "domain nodes currently mirrored from the store"
            ).set_function(lambda: len(self.nodes))
            collector.gauge(
                "binder_store_reverse_entries",
                "IP addresses in the PTR reverse index"
            ).set_function(lambda: len(self.rev_lookup))
            collector.gauge(
                "binder_store_generation",
                "mirror mutation generation counter"
            ).set_function(lambda: self.gen)
            collector.gauge(
                "binder_store_ready",
                "1 when the mirror has a live session and root node"
            ).set_function(lambda: 1.0 if self.is_ready() else 0.0)
            collector.gauge(
                "binder_mirror_staleness_seconds",
                "age of the last change applied to the store mirror "
                "(bounds answer staleness while the session is down)"
            ).set_function(lambda: self.staleness_seconds() or 0.0)
            # zone-scale family (ISSUE 7, docs/observability.md): every
            # figure the large-zone runbook sizes against is scrapeable
            collector.gauge(
                "binder_mirror_names",
                "names (domain nodes) resident in the mirror"
            ).set_function(lambda: float(len(self.nodes)))
            collector.gauge(
                "binder_mirror_interned_names",
                "canonical name/label objects in the interned-name pool"
            ).set_function(lambda: float(len(self.pool)))
            collector.gauge(
                "binder_mirror_rebuild_pending",
                "nodes awaiting re-bind in the chunked session rebuild "
                "(0 when no rebuild is in flight)"
            ).set_function(lambda: float(self.rebuild_pending()))
            collector.gauge(
                "binder_mirror_rebuild_seconds",
                "wall-clock duration of the last completed session "
                "rebuild").set_function(
                    lambda: self.last_rebuild_duration_s or 0.0)
            self._m_rebuild_chunks = collector.counter(
                "binder_mirror_rebuild_chunks",
                "event-loop passes spent draining chunked session "
                "rebuilds").labelled()
            self._m_rebuild_chunks.inc(0)
        store.on_session(self.rebuild)

    def on_mutation(self, cb) -> None:
        """Subscribe to generation bumps (any mirrored store mutation)."""
        self._mutation_cbs.append(cb)

    def on_invalidate(self, cb) -> None:
        """Subscribe to per-name invalidation: cb(tags) where tags is a
        set of lookup domains / PTR qnames whose answers may have
        changed (see TreeNode's watch handlers)."""
        self._invalidate_cbs.append(cb)

    def invalidate(self, tags) -> None:
        if not tags:
            return
        if self.tracer is not None:
            self.tracer.on_mirror_applied()
        for cb in self._invalidate_cbs:
            try:
                cb(tags)
            except Exception:  # noqa: BLE001 — a subscriber bug must
                self.log.exception("invalidate callback failed")  # not stop serving

    def bump_gen(self) -> None:
        self.gen += 1
        if self.tracer is not None:
            self.tracer.on_store_event(self.gen)
        now = time.monotonic()
        self.last_mutation_mono = now
        if self.recorder is not None:
            # watch-storm detection: count mutations per fixed window,
            # flag once per window when the threshold is crossed
            if now - self._storm_window_start > self.STORM_WINDOW:
                self._storm_window_start = now
                self._storm_count = 0
                self._storm_flagged = False
            self._storm_count += 1
            if (self._storm_count >= self.STORM_THRESHOLD
                    and not self._storm_flagged):
                self._storm_flagged = True
                self.recorder.record(
                    "watch-storm", events=self._storm_count,
                    window_s=self.STORM_WINDOW, generation=self.gen)
        for cb in self._mutation_cbs:
            try:
                cb()
            except Exception:  # noqa: BLE001 — a subscriber bug must not
                self.log.exception("mutation callback failed")  # stop serving

    def is_ready(self) -> bool:
        return self.domain in self.nodes

    def staleness_seconds(self) -> Optional[float]:
        """Age of the last applied change (mutation or full rebuild).

        While the store session is live this is ordinary quiet time;
        with the session down it bounds how old the mirror's answers
        may be — the "silent aging" quantity a pure query-side view
        cannot see.  None when nothing was ever mirrored."""
        last = self.last_mutation_mono
        if last is None or (self.last_rebuild_mono is not None
                            and self.last_rebuild_mono > last):
            last = self.last_rebuild_mono
        if last is None:
            return None
        return time.monotonic() - last

    def lookup(self, domain: str) -> Optional[TreeNode]:
        return self.nodes.get(domain)

    def canon(self, name: str) -> str:
        """The canonical object for *name*: the mirror's own domain
        string when the name is mirrored (the nodes index is the
        canonical home for mirrored names), else the process-wide
        interned-name pool.  The answer cache's tag index and the
        compiled-answer table intern through this, so a name is ONE
        object no matter how many layers index it."""
        node = self.nodes.get(name)
        if node is not None:
            return node.domain
        return _names.intern_name(name)

    def reverse_lookup(self, ip: str) -> Optional[TreeNode]:
        return self.rev_lookup.get(ip)

    # -- traced entry points (per-stage attribution) --
    #
    # The resolver hands its QueryCtx in so the mirror probe gets its
    # own phase stamp ("store-lookup") on the query's attribution
    # timeline; the lookup itself is identical.  Kept as separate
    # methods so non-query callers (zone refresh, tests) pay nothing.

    def invalidate_all(self, reason: str = "") -> None:
        """Epoch bump OUTSIDE a rebuild: every answer cached anywhere
        (Python answer cache, compiled table, native C caches, the
        balancer) must revalidate.  Used by the degradation policy at
        state transitions — an answer rendered under one staleness mode
        must never be served under another (e.g. a fresh-rendered wire
        into exhaustion, or an unclamped TTL while stale-serving).

        Deliberately does NOT touch the staleness timestamps: the
        mirror's data did not change, only its permissibility — the
        staleness clock must keep aging."""
        self.epoch += 1
        if self.recorder is not None:
            self.recorder.record("cache-flush", reason=reason,
                                 epoch=self.epoch)
        for cb in self._mutation_cbs:
            try:
                cb()
            except Exception:  # noqa: BLE001 — a subscriber bug must
                self.log.exception("mutation callback failed")  # not stop serving

    def lookup_traced(self, domain: str, query) -> Optional[TreeNode]:
        node = self.nodes.get(domain)
        query.stamp("store-lookup")
        return node

    def reverse_lookup_traced(self, ip: str, query) -> Optional[TreeNode]:
        node = self.rev_lookup.get(ip)
        query.stamp("store-lookup")
        return node

    # -- session rebuild (chunked at zone scale) --

    def rebuild(self) -> None:
        """Re-mirror from scratch-or-current on (re)session
        (lib/zk.js:68-76).

        The walk over EXISTING nodes is chunked: each event-loop pass
        re-registers a time-budgeted batch of watchers and yields, so
        serving (from the still-resident node data) continues and the
        loop-lag watchdog stays quiet through a million-name re-mirror.
        Brand-new subtrees discovered along the way still bind
        synchronously — they are unmirrored content.  Without a running
        loop (synchronous stores, tests, startup before serving) the
        drain runs inline to completion, preserving the historical
        fully-synchronous semantics."""
        if self.m_rebuilds is not None:
            self.m_rebuilds.inc()
        self.last_rebuild_mono = time.monotonic()
        if self.recorder is not None:
            self.recorder.record("mirror-rebuild", epoch=self.epoch + 1,
                                 nodes=len(self.nodes))
        # a (re)session may deliver arbitrary unseen changes while the
        # subtree re-syncs: conservatively invalidate every cached answer
        self.epoch += 1
        tn = self.nodes.get(self.domain)
        if tn is None:
            parts = self.domain.split(".")
            tn = TreeNode(self, ".".join(parts[1:]), parts[0])
        self._rebuild_gen += 1
        self._rebuild_started = time.perf_counter()
        self._rebuild_queue = deque((tn,))
        self._drain_rebuild(self._rebuild_gen)

    def rebuild_pending(self) -> int:
        """Nodes still awaiting re-bind in the in-flight chunked
        rebuild (0 when none is running)."""
        q = self._rebuild_queue
        return len(q) if q is not None else 0

    def rebuild_info(self) -> dict:
        """Introspection block for the /status mirror section."""
        return {
            "pending": self.rebuild_pending(),
            "chunks": self.rebuild_chunks,
            "last_duration_seconds": self.last_rebuild_duration_s,
        }

    def _drain_rebuild(self, gen: int) -> None:
        q = self._rebuild_queue
        while q and gen == self._rebuild_gen:
            t0 = time.perf_counter()
            n = 0
            self.rebuild_chunks += 1
            if self._m_rebuild_chunks is not None:
                self._m_rebuild_chunks.inc()
            while q and gen == self._rebuild_gen:
                node = q.popleft()
                if self.nodes.get(node.domain) is not node:
                    continue            # subtree left mid-walk
                node.rebind_shallow(q)
                n += 1
                if (n >= self.REBUILD_MIN_CHUNK
                        and time.perf_counter() - t0
                        >= self.REBUILD_BUDGET_S):
                    break
            if not q or gen != self._rebuild_gen:
                break
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                continue                # no loop: drain inline
            loop.call_soon(self._rebuild_tick, gen)
            return
        if gen != self._rebuild_gen:
            return                      # superseded by a newer rebuild
        self._rebuild_queue = None
        if self._rebuild_started is not None:
            self.last_rebuild_duration_s = (time.perf_counter()
                                            - self._rebuild_started)
            self._rebuild_started = None
        if self.recorder is not None:
            self.recorder.record(
                "mirror-rebuild-done", epoch=self.epoch,
                nodes=len(self.nodes), chunks=self.rebuild_chunks,
                duration_s=round(self.last_rebuild_duration_s or 0.0, 4))

    def _rebuild_tick(self, gen: int) -> None:
        if gen != self._rebuild_gen:
            return
        self._drain_rebuild(gen)

    def stop(self) -> None:
        self.store.close()
