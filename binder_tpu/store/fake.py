"""In-memory fake coordination store.

Stands in for ZooKeeper in tests and benchmarks — the piece the reference
lacks entirely (SURVEY §4: its tests require a live ZK at 127.0.0.1:2181).
Implements the ``StoreClient`` interface with synchronous watch delivery:

- ``mkdirp/create/set_data/delete/rmr`` mutate the znode tree and fire the
  affected watchers exactly like a ZK server would (children event on the
  parent, data event on the node).
- Initial state is delivered when a listener attaches to a watcher, which
  is when the mirror cache rebinds (matching zkstream's register-then-fetch
  behavior the cache relies on, reference ``lib/zk.js:209-223``).
- ``expire_session()`` simulates ZK session loss + re-establishment: the
  ``session`` callbacks re-fire and the cache rebuilds its watch tree
  (reference ``lib/zk.js:45-47``).
"""
from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional, Tuple

from binder_tpu.store.interface import (SessionStateMixin, StoreClient,
                                        Watcher)


class _Node:
    __slots__ = ("data", "children")

    def __init__(self, data: bytes = b"") -> None:
        self.data = data
        self.children: Dict[str, _Node] = {}


class FakeStore(SessionStateMixin, StoreClient):
    def __init__(self, recorder=None) -> None:
        self._init_session_state(recorder)
        self._root = _Node()
        self._watchers: Dict[str, Watcher] = {}
        self._session_cbs: List[Callable[[], None]] = []
        self._connected = False

    # -- StoreClient interface --

    def on_session(self, cb: Callable[[], None]) -> None:
        self._session_cbs.append(cb)
        if self._connected:
            cb()

    def watcher(self, path: str) -> Watcher:
        w = self._watchers.get(path)
        if w is None:
            w = _FakeWatcher(self, path)
            self._watchers[path] = w
        return w

    def is_connected(self) -> bool:
        return self._connected

    def close(self) -> None:
        self._session_transition("closed", "close() called")
        self._connected = False

    # -- session simulation --

    def start_session(self) -> None:
        self._connected = True
        self._session_transition("connected", "start_session")
        for cb in list(self._session_cbs):
            cb()

    def expire_session(self) -> None:
        """Session loss immediately followed by a new session."""
        self._connected = False
        self._session_transition("expired", "expire_session")
        self.start_session()

    def lose_session(self) -> None:
        """Session loss with NO re-establishment: the store goes dark
        and the mirror starts aging — the silent staleness failure the
        introspection layer exists to surface."""
        self._connected = False
        self._session_transition("degraded", "lose_session")

    # -- tree access --

    def _find(self, path: str) -> Optional[_Node]:
        node = self._root
        for part in _parts(path):
            node = node.children.get(part)
            if node is None:
                return None
        return node

    def exists(self, path: str) -> bool:
        return self._find(path) is not None

    def get_data(self, path: str) -> Optional[bytes]:
        n = self._find(path)
        return None if n is None else n.data

    def get_children(self, path: str) -> Optional[List[str]]:
        n = self._find(path)
        return None if n is None else sorted(n.children)

    # -- mutations (the registrar-equivalent write surface) --

    def mkdirp(self, path: str, data: bytes = b"") -> None:
        """Create *path* and any missing parents (test/helper.js zkMkdirP
        analog, reference ``test/helper.js:98-129``)."""
        node = self._root
        parent_path = "/"
        prefix = ""
        for part in _parts(path):
            prefix += "/" + part
            child = node.children.get(part)
            if child is None:
                child = _Node()
                node.children[part] = child
                self._fire_children(parent_path, node)
            node = child
            parent_path = prefix
        if data:
            node.data = data
            self._fire_data(prefix, node)

    def create(self, path: str, data: bytes = b"") -> None:
        parent_path, name = _split(path)
        parent = self._find(parent_path)
        if parent is None:
            raise KeyError(f"no such parent: {parent_path}")
        if name in parent.children:
            raise KeyError(f"node exists: {path}")
        parent.children[name] = _Node(data)
        self._fire_children(parent_path, parent)
        if data:
            self._fire_data(path, parent.children[name])

    def set_data(self, path: str, data: bytes) -> None:
        node = self._find(path)
        if node is None:
            raise KeyError(f"no such node: {path}")
        node.data = data
        self._fire_data(path, node)

    def delete(self, path: str) -> None:
        parent_path, name = _split(path)
        parent = self._find(parent_path)
        if parent is None or name not in parent.children:
            raise KeyError(f"no such node: {path}")
        if parent.children[name].children:
            raise KeyError(f"node has children: {path}")
        del parent.children[name]
        self._fire_children(parent_path, parent)

    def rmr(self, path: str) -> None:
        """Recursive delete (test/helper.js zkRmr analog)."""
        node = self._find(path)
        if node is None:
            return
        for kid in list(node.children):
            self.rmr(path.rstrip("/") + "/" + kid)
        self.delete(path)

    # convenience for fixtures
    def put_json(self, path: str, obj) -> None:
        data = json.dumps(obj).encode("utf-8")
        if self.exists(path):
            self.set_data(path, data)
        else:
            self.mkdirp(path, data)

    # -- watch plumbing --

    def _fire_children(self, path: str, node: _Node) -> None:
        w = self._watchers.get(path)
        if w is not None and self._connected:
            w.emit("children", sorted(node.children))

    def _fire_data(self, path: str, node: _Node) -> None:
        w = self._watchers.get(path)
        if w is not None and self._connected:
            w.emit("data", node.data)


class _FakeWatcher(Watcher):
    """Watcher that delivers current state as soon as a listener attaches."""

    def __init__(self, store: FakeStore, path: str) -> None:
        super().__init__(path)
        self._store = store

    def on(self, event: str, cb: Callable) -> None:
        super().on(event, cb)
        node = self._store._find(self.path)
        if node is None or not self._store._connected:
            return
        if event == "children":
            cb(sorted(node.children))
        elif event == "data":
            cb(node.data)


def _parts(path: str) -> List[str]:
    return [p for p in path.split("/") if p]


def _split(path: str) -> Tuple[str, str]:
    parts = _parts(path)
    if not parts:
        raise KeyError("cannot operate on root")
    return "/" + "/".join(parts[:-1]), parts[-1]
