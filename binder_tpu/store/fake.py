"""In-memory fake coordination store.

Stands in for ZooKeeper in tests and benchmarks — the piece the reference
lacks entirely (SURVEY §4: its tests require a live ZK at 127.0.0.1:2181).
Implements the ``StoreClient`` interface with synchronous watch delivery:

- ``mkdirp/create/set_data/delete/rmr`` mutate the znode tree and fire the
  affected watchers exactly like a ZK server would (children event on the
  parent, data event on the node).
- Initial state is delivered when a listener attaches to a watcher, which
  is when the mirror cache rebinds (matching zkstream's register-then-fetch
  behavior the cache relies on, reference ``lib/zk.js:209-223``).
- ``expire_session()`` simulates ZK session loss + re-establishment: the
  ``session`` callbacks re-fire and the cache rebuilds its watch tree
  (reference ``lib/zk.js:45-47``).
"""
from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional, Tuple

from binder_tpu.store.interface import (SessionStateMixin, StoreClient,
                                        Watcher)


class _Node:
    __slots__ = ("data", "children")

    def __init__(self, data: bytes = b"") -> None:
        self.data = data
        self.children: Dict[str, _Node] = {}


class FakeStore(SessionStateMixin, StoreClient):
    def __init__(self, recorder=None) -> None:
        self._init_session_state(recorder)
        self._root = _Node()
        self._watchers: Dict[str, Watcher] = {}
        # mirror fast binding: registered node SOURCES (a MirrorCache's
        # domain->TreeNode index).  Events route straight to the bound
        # node by domain — no Watcher object, no stored path string, no
        # binding dict of our own: the mirror's node index IS the watch
        # table, so the per-znode watch costs literally nothing extra.
        # That is what makes a million-name mirror affordable.
        self._sources: List[Dict[str, object]] = []
        self._session_cbs: List[Callable[[], None]] = []
        self._connected = False

    # -- StoreClient interface --

    def on_session(self, cb: Callable[[], None]) -> None:
        self._session_cbs.append(cb)
        if self._connected:
            cb()

    def watcher(self, path: str) -> Watcher:
        w = self._watchers.get(path)
        if w is None:
            w = _FakeWatcher(self, path)
            self._watchers[path] = w
        return w

    def bind_source(self, nodes: Dict[str, object]) -> bool:
        """Register a mirror's domain->node index as the watch table:
        fired events route to ``nodes[domain]`` directly."""
        if nodes not in self._sources:
            self._sources.append(nodes)
        return True

    def bind_node(self, path: str, node) -> None:
        """With source routing the bind itself is just the initial
        state delivery — membership in the mirror's node index (the
        registered source) is what keeps events flowing."""
        n = self._find(path)
        if n is None or not self._connected:
            return
        # same delivery order as the generic watcher path: children
        # (creating the kid nodes) before data
        node.on_children_changed(sorted(n.children))
        node.on_data_changed(n.data)

    def unbind_node(self, path: str, node) -> None:
        """No-op: unbinding is the node leaving its mirror's index."""

    def is_connected(self) -> bool:
        return self._connected

    def close(self) -> None:
        self._session_transition("closed", "close() called")
        self._connected = False

    # -- session simulation --

    def start_session(self) -> None:
        self._connected = True
        self._session_transition("connected", "start_session")
        for cb in list(self._session_cbs):
            cb()

    def expire_session(self) -> None:
        """Session loss immediately followed by a new session."""
        self._connected = False
        self._session_transition("expired", "expire_session")
        self.start_session()

    def lose_session(self) -> None:
        """Session loss with NO re-establishment: the store goes dark
        and the mirror starts aging — the silent staleness failure the
        introspection layer exists to surface."""
        self._connected = False
        self._session_transition("degraded", "lose_session")

    # -- tree access --

    def _find(self, path: str) -> Optional[_Node]:
        node = self._root
        for part in _parts(path):
            node = node.children.get(part)
            if node is None:
                return None
        return node

    def exists(self, path: str) -> bool:
        return self._find(path) is not None

    def get_data(self, path: str) -> Optional[bytes]:
        n = self._find(path)
        return None if n is None else n.data

    def get_children(self, path: str) -> Optional[List[str]]:
        n = self._find(path)
        return None if n is None else sorted(n.children)

    # -- mutations (the registrar-equivalent write surface) --

    def mkdirp(self, path: str, data: bytes = b"") -> None:
        """Create *path* and any missing parents (test/helper.js zkMkdirP
        analog, reference ``test/helper.js:98-129``)."""
        node = self._root
        parent_path = "/"
        prefix = ""
        for part in _parts(path):
            prefix += "/" + part
            child = node.children.get(part)
            if child is None:
                child = _Node()
                node.children[part] = child
                self._fire_children(parent_path, node)
            node = child
            parent_path = prefix
        if data:
            node.data = data
            self._fire_data(prefix, node)

    def create(self, path: str, data: bytes = b"") -> None:
        parent_path, name = _split(path)
        parent = self._find(parent_path)
        if parent is None:
            raise KeyError(f"no such parent: {parent_path}")
        if name in parent.children:
            raise KeyError(f"node exists: {path}")
        parent.children[name] = _Node(data)
        self._fire_children(parent_path, parent)
        if data:
            self._fire_data(path, parent.children[name])

    def set_data(self, path: str, data: bytes) -> None:
        node = self._find(path)
        if node is None:
            raise KeyError(f"no such node: {path}")
        node.data = data
        self._fire_data(path, node)

    def delete(self, path: str) -> None:
        parent_path, name = _split(path)
        parent = self._find(parent_path)
        if parent is None or name not in parent.children:
            raise KeyError(f"no such node: {path}")
        if parent.children[name].children:
            raise KeyError(f"node has children: {path}")
        del parent.children[name]
        self._fire_children(parent_path, parent)

    def rmr(self, path: str) -> None:
        """Recursive delete (test/helper.js zkRmr analog)."""
        node = self._find(path)
        if node is None:
            return
        for kid in list(node.children):
            self.rmr(path.rstrip("/") + "/" + kid)
        self.delete(path)

    # convenience for fixtures
    def put_json(self, path: str, obj) -> None:
        data = json.dumps(obj).encode("utf-8")
        if self.exists(path):
            self.set_data(path, data)
        else:
            self.mkdirp(path, data)

    # -- watch plumbing --

    def _fire_children(self, path: str, node: _Node) -> None:
        if not self._connected:
            return
        w = self._watchers.get(path)
        if w is not None:
            w.emit("children", sorted(node.children))
        if self._sources:
            dom = _path_domain(path)
            for src in self._sources:
                tn = src.get(dom)
                if tn is not None:
                    tn.on_children_changed(sorted(node.children))

    def _fire_data(self, path: str, node: _Node) -> None:
        if not self._connected:
            return
        w = self._watchers.get(path)
        if w is not None:
            w.emit("data", node.data)
        if self._sources:
            dom = _path_domain(path)
            for src in self._sources:
                tn = src.get(dom)
                if tn is not None:
                    tn.on_data_changed(node.data)


class _FakeWatcher(Watcher):
    """Watcher that delivers current state as soon as a listener attaches."""

    __slots__ = ("_store",)

    def __init__(self, store: FakeStore, path: str) -> None:
        super().__init__(path)
        self._store = store

    def on(self, event: str, cb: Callable) -> None:
        super().on(event, cb)
        node = self._store._find(self.path)
        if node is None or not self._store._connected:
            return
        if event == "children":
            cb(sorted(node.children))
        elif event == "data":
            cb(node.data)

    def bind_node(self, tn) -> None:
        super().bind_node(tn)
        node = self._store._find(self.path)
        if node is None or not self._store._connected:
            return
        # same delivery order as two on() calls: children (creating the
        # kid nodes) before data
        tn.on_children_changed(sorted(node.children))
        tn.on_data_changed(node.data)


def populate_synthetic(store: FakeStore, domain: str, hosts: int,
                       racks: int = 0,
                       subtree: str = "zs") -> int:
    """Bulk-build a synthetic production-scale zone directly into the
    store tree (bench/smoke surface, ISSUE 7 zone_scale axis): ``hosts``
    host records spread across ``racks`` service-style parents under
    ``<subtree>.<domain>``, with deterministic unique addresses.

    Builds by direct tree insertion — watcher firing is pointless
    before a session starts, and at a million names the per-node
    ``mkdirp`` path walk would dominate the build.  Call BEFORE
    ``start_session()``; the mirror picks the whole zone up on its
    initial build.  Returns the number of host nodes created."""
    if racks <= 0:
        racks = max(1, min(1024, hosts // 512))
    base = [p for p in reversed((subtree + "." + domain).split("."))
            if p]
    node = store._root
    for part in base:
        nxt = node.children.get(part)
        if nxt is None:
            nxt = _Node()
            node.children[part] = nxt
        node = nxt
    rack_nodes = []
    for r in range(racks):
        rn = _Node(b'{"type": "service", "service": {"srvce": "_zs", '
                   b'"proto": "_tcp", "port": 80}}')
        node.children[f"r{r:04d}"] = rn
        rack_nodes.append(rn)
    for i in range(hosts):
        addr = f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}"
        rack_nodes[i % racks].children[f"h{i:06d}"] = _Node(
            b'{"type": "host", "host": {"address": "%s"}}'
            % addr.encode())
    return hosts


def _parts(path: str) -> List[str]:
    return [p for p in path.split("/") if p]


def _path_domain(path: str) -> str:
    """``/com/foo/web -> web.foo.com`` — the (case-preserving) inverse
    of ``cache.domain_to_path``, used to route fired events to bound
    mirror nodes.  Case sensitivity matches the historical exact-path
    watcher match: a store path whose case differs from the mirror's
    lowercased registration never matched before and still doesn't."""
    return ".".join(reversed([p for p in path.split("/") if p]))


def _split(path: str) -> Tuple[str, str]:
    parts = _parts(path)
    if not parts:
        raise KeyError("cannot operate on root")
    return "/" + "/".join(parts[:-1]), parts[-1]
