"""Coordination-store layer: client interface, fake store, mirror cache."""
from binder_tpu.store.cache import (  # noqa: F401
    HOST_TYPES,
    MirrorCache,
    TreeNode,
    domain_to_path,
)
from binder_tpu.store.fake import FakeStore  # noqa: F401
from binder_tpu.store.interface import StoreClient, Watcher  # noqa: F401
