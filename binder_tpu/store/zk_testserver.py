"""In-process ZooKeeper server speaking the real wire protocol.

Test double for ``zk_client.py`` — lets the ZK client, mirror cache, and
full binder stack be exercised against the actual jute protocol without a
ZooKeeper installation (this image has none).  Implements the subset the
client uses: session handshake/resume/expiry, ping, getChildren2,
getData, exists (all with one-shot watches), create, setData, delete,
closeSession.

Ensemble semantics: several servers constructed over one shared
``ZKEnsembleState`` behave like members of a quorum from the client's
point of view — the tree, the session table, and zxids are common, so a
session established through one member survives a failover to another
(the ZAB-replicated-session behavior of the production co-located
ensemble, reference README.md:36-39).  Watch registrations also live in
the shared state; combined with the client's re-arm-on-reconnect pass
this makes the failover path testable end to end.  Each server only
severs its *own* connections on stop(), exactly like losing one member.

Production deployments point ``store.backend=zookeeper`` at a real
ensemble; this server exists so the protocol path has automated coverage
the reference never had (its tests require a live ZK at 127.0.0.1:2181,
SURVEY §4).
"""
from __future__ import annotations

import asyncio
import logging
import struct
from typing import Dict, Optional, Set, Tuple

from binder_tpu.store import jute
from binder_tpu.store.jute import Buf, Err, EventType, KeeperState, OpCode


class _Node:
    __slots__ = ("data", "children", "version", "cversion")

    def __init__(self, data: bytes = b"") -> None:
        self.data = data
        self.children: Dict[str, _Node] = {}
        self.version = 0
        self.cversion = 0


class _Session:
    def __init__(self, session_id: int, timeout_ms: int) -> None:
        self.id = session_id
        self.passwd = session_id.to_bytes(8, "big") * 2
        self.timeout_ms = timeout_ms
        self.writer: Optional[asyncio.StreamWriter] = None
        self.expired = False


class ZKEnsembleState:
    """State shared by every member of a test ensemble: the replicated
    tree, the session table, the zxid counter, and watch registrations
    (path -> set of session ids, per watch class)."""

    def __init__(self) -> None:
        self.root = _Node()
        self.sessions: Dict[int, _Session] = {}
        self.next_session = 0x10_0000_0000_0001
        self.zxid = 0
        self.data_watches: Dict[str, Set[int]] = {}
        self.child_watches: Dict[str, Set[int]] = {}
        self.exists_watches: Dict[str, Set[int]] = {}


class ZKTestServer:
    def __init__(self, log: Optional[logging.Logger] = None,
                 state: Optional[ZKEnsembleState] = None) -> None:
        self.log = log or logging.getLogger("binder.zktest")
        # pass the same ZKEnsembleState to several servers to model a
        # quorum; default is a standalone single-member "ensemble"
        self.state = state if state is not None else ZKEnsembleState()
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None
        # connections accepted by THIS member (stop() must only sever
        # these, not sessions served by sibling members)
        self._conns: Set[asyncio.StreamWriter] = set()
        self.dropped_conns = 0

    # -- lifecycle --

    async def start(self, address: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._conn, address, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        # sever live connections BEFORE wait_closed(): since 3.12 it
        # waits for connection handlers too, and a handler blocked in a
        # read only exits once its writer (same transport) is closed —
        # the old order deadlocked when a client was still connected.
        # Only THIS member's connections are severed; sessions survive in
        # the shared state for the surviving members to resume.
        for w in list(self._conns):
            w.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    def expire_session(self, session_id: Optional[int] = None) -> None:
        """Mark session(s) expired and drop their connections — the test
        hook for session-loss behavior."""
        for s in list(self.state.sessions.values()):
            if session_id is None or s.id == session_id:
                s.expired = True
                if s.writer is not None:
                    s.writer.close()

    def drop_connections(self) -> None:
        """Sever this member's connections without expiring sessions
        (network blip)."""
        for w in list(self._conns):
            self.dropped_conns += 1
            w.close()

    # -- tree helpers --

    def _find(self, path: str) -> Optional[_Node]:
        node = self.state.root
        for part in [p for p in path.split("/") if p]:
            node = node.children.get(part)
            if node is None:
                return None
        return node

    @staticmethod
    def _split(path: str) -> Tuple[str, str]:
        parts = [p for p in path.split("/") if p]
        return "/" + "/".join(parts[:-1]), parts[-1]

    # -- watch firing (one-shot, like the real server) --

    def _fire(self, table: Dict[str, Set[int]], path: str,
              etype: int) -> None:
        sessions = table.pop(path, set())
        payload = (jute.i32(jute.XID_WATCHER_EVENT)
                   + jute.i64(self.state.zxid)
                   + jute.i32(0) + jute.i32(etype)
                   + jute.i32(KeeperState.SYNC_CONNECTED)
                   + jute.string(path))
        for sid in sessions:
            s = self.state.sessions.get(sid)
            if s is not None and s.writer is not None and not s.expired:
                try:
                    s.writer.write(jute.frame(payload))
                except Exception:  # noqa: BLE001
                    pass

    # -- connection handling --

    async def _conn(self, reader: asyncio.StreamReader,
                    writer: asyncio.StreamWriter) -> None:
        session: Optional[_Session] = None
        self._conns.add(writer)
        try:
            # handshake
            req = Buf(await self._read_frame(reader))
            req.i32()          # protocol version
            req.i64()          # lastZxidSeen
            timeout = req.i32()
            session_id = req.i64()
            req.buffer()       # passwd
            # (optional readOnly flag ignored)

            if session_id != 0:
                old = self.state.sessions.get(session_id)
                if old is None or old.expired:
                    # expired: per protocol, answer with session 0
                    writer.write(jute.frame(
                        jute.i32(0) + jute.i32(0) + jute.i64(0)
                        + jute.buffer(b"\x00" * 16) + jute.boolean(False)))
                    await writer.drain()
                    return
                session = old
            else:
                session = _Session(self.state.next_session, timeout)
                self.state.next_session += 1
                self.state.sessions[session.id] = session
            session.writer = writer
            writer.write(jute.frame(
                jute.i32(0) + jute.i32(session.timeout_ms)
                + jute.i64(session.id) + jute.buffer(session.passwd)
                + jute.boolean(False)))
            await writer.drain()

            while True:
                buf = Buf(await self._read_frame(reader))
                xid = buf.i32()
                opcode = buf.i32()
                if opcode == OpCode.PING:
                    writer.write(jute.frame(
                        jute.i32(jute.XID_PING) + jute.i64(self.state.zxid)
                        + jute.i32(0)))
                    await writer.drain()
                    continue
                if opcode == OpCode.CLOSE:
                    writer.write(jute.frame(
                        jute.i32(xid) + jute.i64(self.state.zxid) + jute.i32(0)))
                    await writer.drain()
                    return
                err, body = self._handle(session, opcode, buf)
                writer.write(jute.frame(
                    jute.i32(xid) + jute.i64(self.state.zxid) + jute.i32(err)
                    + body))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                ValueError):
            pass
        finally:
            self._conns.discard(writer)
            if session is not None and session.writer is writer:
                session.writer = None
            writer.close()

    async def _read_frame(self, reader: asyncio.StreamReader) -> bytes:
        hdr = await reader.readexactly(4)
        (length,) = struct.unpack(">i", hdr)
        if length < 0 or length > 4 * 1024 * 1024:
            raise ValueError("bad frame")
        return await reader.readexactly(length)

    # -- op dispatch --

    def _handle(self, session: _Session, opcode: int,
                buf: Buf) -> Tuple[int, bytes]:
        if opcode == OpCode.GETCHILDREN2 or opcode == OpCode.GETCHILDREN:
            path = buf.string()
            watch = buf.boolean()
            node = self._find(path)
            if node is None:
                if watch:
                    self.state.exists_watches.setdefault(path,
                                                    set()).add(session.id)
                return Err.NONODE, b""
            if watch:
                self.state.child_watches.setdefault(path, set()).add(session.id)
            out = jute.i32(len(node.children))
            for name in sorted(node.children):
                out += jute.string(name)
            if opcode == OpCode.GETCHILDREN2:
                out += jute.pack_stat(version=node.version,
                                      cversion=node.cversion,
                                      data_length=len(node.data),
                                      num_children=len(node.children))
            return Err.OK, out

        if opcode == OpCode.GETDATA:
            path = buf.string()
            watch = buf.boolean()
            node = self._find(path)
            if node is None:
                if watch:
                    self.state.exists_watches.setdefault(path,
                                                    set()).add(session.id)
                return Err.NONODE, b""
            if watch:
                self.state.data_watches.setdefault(path, set()).add(session.id)
            # numChildren must be real: the shared-watch client decides
            # from this stat whether the node is a directory that needs
            # its own children watch (zk_client._sync_shared)
            return Err.OK, (jute.buffer(node.data)
                            + jute.pack_stat(version=node.version,
                                             data_length=len(node.data),
                                             num_children=len(node.children)))

        if opcode == OpCode.EXISTS:
            path = buf.string()
            watch = buf.boolean()
            node = self._find(path)
            if node is None:
                if watch:
                    self.state.exists_watches.setdefault(path,
                                                    set()).add(session.id)
                return Err.NONODE, b""
            if watch:
                self.state.data_watches.setdefault(path, set()).add(session.id)
            return Err.OK, jute.pack_stat(version=node.version,
                                          data_length=len(node.data))

        if opcode == OpCode.CREATE:
            path = buf.string()
            data = buf.buffer() or b""
            parent_path, name = self._split(path)
            parent = self._find(parent_path)
            if parent is None:
                return Err.NONODE, b""
            if name in parent.children:
                return Err.NODEEXISTS, b""
            self.state.zxid += 1
            parent.children[name] = _Node(data)
            parent.cversion += 1
            self._fire(self.state.exists_watches, path, EventType.CREATED)
            self._fire(self.state.child_watches, parent_path,
                       EventType.CHILDREN_CHANGED)
            return Err.OK, jute.string(path)

        if opcode == OpCode.SETDATA:
            path = buf.string()
            data = buf.buffer() or b""
            node = self._find(path)
            if node is None:
                return Err.NONODE, b""
            self.state.zxid += 1
            node.data = data
            node.version += 1
            self._fire(self.state.data_watches, path, EventType.DATA_CHANGED)
            return Err.OK, jute.pack_stat(version=node.version,
                                          data_length=len(data))

        if opcode == OpCode.DELETE:
            path = buf.string()
            parent_path, name = self._split(path)
            parent = self._find(parent_path)
            if parent is None or name not in parent.children:
                return Err.NONODE, b""
            if parent.children[name].children:
                return Err.NOTEMPTY, b""
            self.state.zxid += 1
            del parent.children[name]
            parent.cversion += 1
            self._fire(self.state.data_watches, path, EventType.DELETED)
            self._fire(self.state.child_watches, path, EventType.DELETED)
            self._fire(self.state.child_watches, parent_path,
                       EventType.CHILDREN_CHANGED)
            return Err.OK, b""

        self.log.warning("zktest: unsupported opcode %d", opcode)
        return Err.OK, b""


def main() -> None:
    """Run standalone: python -m binder_tpu.store.zk_testserver [port]."""
    import sys

    async def _run():
        server = ZKTestServer()
        port = await server.start(
            port=int(sys.argv[1]) if len(sys.argv) > 1 else 2181)
        print(f"zk-testserver listening on 127.0.0.1:{port}", flush=True)
        await asyncio.Event().wait()

    try:
        asyncio.run(_run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
