"""ZooKeeper jute wire-format primitives + protocol constants.

Shared by the client (``zk_client.py``) and the in-process test server
(``zk_testserver.py``).  The format is the public ZooKeeper client
protocol: big-endian primitives, length-prefixed frames, and the opcode
set of ZooKeeper 3.4 (the version the reference deploys against,
reference ``Makefile:75-77``).
"""
from __future__ import annotations

import struct
from typing import Optional


class OpCode:
    NOTIFICATION = 0
    CREATE = 1
    DELETE = 2
    EXISTS = 3
    GETDATA = 4
    SETDATA = 5
    GETCHILDREN = 8
    SYNC = 9
    PING = 11
    GETCHILDREN2 = 12
    CLOSE = -11
    SETWATCHES = 101


class Err:
    OK = 0
    NONODE = -101
    NODEEXISTS = -110
    NOTEMPTY = -111
    SESSIONEXPIRED = -112
    BADVERSION = -103


class EventType:
    CREATED = 1
    DELETED = 2
    DATA_CHANGED = 3
    CHILDREN_CHANGED = 4


class KeeperState:
    SYNC_CONNECTED = 3
    EXPIRED = -112


# xids with special meaning on the wire
XID_WATCHER_EVENT = -1
XID_PING = -2


class Buf:
    """Bounds-checked big-endian reader."""

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.off = 0

    def _take(self, n: int) -> bytes:
        if self.off + n > len(self.data):
            raise ValueError("jute: short read")
        out = self.data[self.off:self.off + n]
        self.off += n
        return out

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def boolean(self) -> bool:
        return self._take(1)[0] != 0

    def buffer(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        return self._take(n)

    def string(self) -> str:
        b = self.buffer()
        return "" if b is None else b.decode("utf-8")

    def remaining(self) -> int:
        return len(self.data) - self.off


def i32(v: int) -> bytes:
    return struct.pack(">i", v)


def i64(v: int) -> bytes:
    return struct.pack(">q", v)


def boolean(v: bool) -> bytes:
    return b"\x01" if v else b"\x00"


def buffer(b: Optional[bytes]) -> bytes:
    if b is None:
        return i32(-1)
    return i32(len(b)) + b


def string(s: str) -> bytes:
    return buffer(s.encode("utf-8"))


def frame(payload: bytes) -> bytes:
    return i32(len(payload)) + payload


# Stat record: czxid, mzxid, ctime, mtime (i64); version, cversion,
# aversion (i32); ephemeralOwner (i64); dataLength, numChildren (i32);
# pzxid (i64)
STAT_FMT = ">qqqqiiiqiiq"
STAT_LEN = struct.calcsize(STAT_FMT)


def pack_stat(czxid=0, mzxid=0, ctime=0, mtime=0, version=0, cversion=0,
              aversion=0, ephemeral_owner=0, data_length=0,
              num_children=0, pzxid=0) -> bytes:
    return struct.pack(STAT_FMT, czxid, mzxid, ctime, mtime, version,
                       cversion, aversion, ephemeral_owner, data_length,
                       num_children, pzxid)


def read_stat(buf: Buf) -> dict:
    vals = struct.unpack(STAT_FMT, buf._take(STAT_LEN))
    keys = ("czxid", "mzxid", "ctime", "mtime", "version", "cversion",
            "aversion", "ephemeralOwner", "dataLength", "numChildren",
            "pzxid")
    return dict(zip(keys, vals))
