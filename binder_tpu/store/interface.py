"""Coordination-store client interface.

The reference binds its cache directly to zkstream (reference
``lib/zk.js:33-39``) — its biggest testability gap (SURVEY §4: every test
needs a live ZooKeeper).  The rebuild defines this narrow interface instead,
with two implementations:

- ``binder_tpu.store.fake.FakeStore`` — in-memory, synchronous; used by
  tests and ``bench.py``.
- ``binder_tpu.store.zk_client.ZKClient`` — real ZooKeeper wire protocol
  (jute) over asyncio.

Semantics modeled on zkstream's surface as consumed by the cache:

- The client emits a ``session`` event whenever a (new) session is
  established; the cache responds by re-binding its whole watch tree
  (reference ``lib/zk.js:45-47``).
- ``watcher(path)`` returns a ``Watcher`` handle.  Registering listeners is
  idempotent w.r.t. rebinds: the cache clears listeners and re-adds them on
  every rebind.  After (re)registration the store fires the current state —
  a ``children`` event with the current child names and a ``data`` event
  with the current node bytes — and again on every subsequent change.
- Watch events carry state, not deltas: ``children`` always delivers the
  full current child list.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, List

#: Session states shared by every StoreClient implementation.  The
#: distinction between "never-connected" and "degraded" is the one the
#: plain is_connected() bool could not express: a binder that has not
#: yet reached its ensemble serves nothing, while one whose session was
#: lost keeps serving an aging mirror — operationally very different
#: failures (the second is the silent one the introspection layer
#: exists to surface).
SESSION_STATES = ("never-connected", "connected", "degraded", "expired",
                  "closed")


class SessionStateMixin:
    """Session state machine + transition history for store clients.

    Tracks the exact monotonic timestamp of every state transition so
    ``disconnected_seconds()`` is measured, never inferred, and keeps a
    bounded transition history (the reconnect/backoff record served by
    the introspection snapshot).  An optional flight recorder receives
    a ``session-transition`` event per edge."""

    def _init_session_state(self, recorder=None, history: int = 64) -> None:
        self._session_state = "never-connected"
        self._state_since = time.monotonic()
        # monotonic instant the session was lost (set on leaving
        # "connected", cleared on re-entering it); None while connected
        # or never connected
        self._disconnected_since = None
        self.session_establishments = 0
        self._transitions = deque(maxlen=history)
        self._session_recorder = recorder

    def _session_transition(self, new: str, reason: str = "") -> None:
        old = self._session_state
        if new == old:
            return
        now = time.monotonic()
        self._session_state = new
        self._state_since = now
        if new == "connected":
            self._disconnected_since = None
            self.session_establishments += 1
        elif old == "connected":
            self._disconnected_since = now
        self._transitions.append({
            "t_mono": now, "t_wall": time.time(),
            "from": old, "to": new, "reason": reason,
        })
        rec = self._session_recorder
        if rec is not None:
            rec.record("session-transition", frm=old, to=new,
                       reason=reason)

    def session_state(self) -> str:
        return self._session_state

    def disconnected_seconds(self):
        """Exact seconds since the session was lost: 0.0 while
        connected, None when no session was ever established (there is
        no loss instant to measure from), else the measured age of the
        connected→lost transition."""
        if self._session_state == "connected":
            return 0.0
        if self._disconnected_since is None:
            return None
        return time.monotonic() - self._disconnected_since

    def session_transitions(self) -> List[dict]:
        """Bounded transition history, oldest first."""
        return list(self._transitions)


class Watcher:
    """Per-path watch handle: holds ``children`` and ``data`` listeners.

    Mirrors zkstream's watcher EventEmitter surface (``childrenChanged`` /
    ``dataChanged``) as used at reference ``lib/zk.js:215-219``.

    Storage is deliberately compact (one watcher per mirrored znode
    means a million of these at production zone scale): slots instead
    of a ``__dict__``, and each event's listeners held as None / the
    single callback / a tuple — the mirror registers exactly one per
    event, so the common case allocates no container at all.  The
    ``_listeners`` dict view is materialized on demand for
    introspection and tests.
    """

    __slots__ = ("path", "_children", "_data")

    def __init__(self, path: str) -> None:
        self.path = path
        self._children = None
        self._data = None

    @staticmethod
    def _add(slot, cb):
        if slot is None:
            return cb
        if type(slot) is tuple:
            return slot + (cb,)
        return (slot, cb)

    def on(self, event: str, cb: Callable) -> None:
        if event == "children":
            self._children = self._add(self._children, cb)
        elif event == "data":
            self._data = self._add(self._data, cb)
        else:
            raise KeyError(event)

    def bind_node(self, node) -> None:
        """Attach a mirror TreeNode as the listener for BOTH events.

        The node object itself is stored and its
        ``on_children_changed``/``on_data_changed`` handlers are
        resolved at emit time — one reference instead of two
        bound-method objects, which at one watcher per znode is tens of
        MB at production zone scale.  Subclasses that deliver initial
        state on listener attach must override this the same way they
        override ``on``."""
        self._children = self._add(self._children, node)
        self._data = self._add(self._data, node)

    def clear(self) -> None:
        """Remove all listeners (reference removeAllListeners,
        ``lib/zk.js:211-214``)."""
        self._children = None
        self._data = None

    @staticmethod
    def _resolve(entry, event: str) -> Callable:
        if callable(entry):
            return entry
        return (entry.on_children_changed if event == "children"
                else entry.on_data_changed)

    def emit(self, event: str, *args) -> None:
        slot = self._children if event == "children" else self._data
        if slot is None:
            return
        if type(slot) is tuple:
            for entry in slot:
                self._resolve(entry, event)(*args)
        else:
            self._resolve(slot, event)(*args)

    @property
    def _listeners(self) -> Dict[str, List[Callable]]:
        """Dict-of-lists view of the compact listener slots (kept for
        tests/introspection; mutations to the view are NOT applied)."""
        out = {}
        for event, slot in (("children", self._children),
                            ("data", self._data)):
            if slot is None:
                out[event] = []
            elif type(slot) is tuple:
                out[event] = [self._resolve(e, event) for e in slot]
            else:
                out[event] = [self._resolve(slot, event)]
        return out

    @property
    def has_listeners(self) -> bool:
        return self._children is not None or self._data is not None


class StoreClient:
    """Abstract coordination-store client (zkstream-equivalent surface)."""

    def on_session(self, cb: Callable[[], None]) -> None:
        """Register a callback fired on every session (re-)establishment."""
        raise NotImplementedError

    def watcher(self, path: str) -> Watcher:
        """Return the watch handle for *path* (created on first use).

        After the caller attaches listeners, the store must deliver the
        current state of the node (children + data) and keep delivering on
        changes, for as long as the session lasts.
        """
        raise NotImplementedError

    # -- mirror-node fast binding --
    #
    # The mirror registers EXACTLY one listener pair per znode — one
    # TreeNode.  The generic path (watcher object + listener slots) is
    # ~190 bytes per node, which at a million names is the difference
    # between a mirror that fits and one that doesn't.  Stores that can
    # route events straight to a bound node override these with a bare
    # domain->node dict: the fake store and the shard replica feed
    # route synchronously, and the real ZooKeeper client uses the index
    # both for dispatch and to batch its wire watches (one data watch
    # per znode, children watches only where children can exist —
    # zk_client module docstring).  The default declines and keeps the
    # historical per-path watcher semantics.

    def bind_source(self, nodes) -> bool:
        """Offer the mirror's domain->node index as a direct event
        routing table.  Stores that can route events by domain accept
        and return True — per-node binds then carry no per-node state
        at all.  The default declines; such stores keep per-path
        watcher objects."""
        return False

    def bind_node(self, path: str, node) -> None:
        """Bind *node* as the sole listener for *path*: clears any
        previous listeners, attaches the node for both events, and
        delivers current state (same contract as two ``on`` calls)."""
        w = self.watcher(path)
        w.clear()
        w.bind_node(node)

    def unbind_node(self, path: str, node) -> None:
        """Detach *node* from *path* (no-op if it is not bound)."""
        self.watcher(path).clear()

    def is_connected(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError
