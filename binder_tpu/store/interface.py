"""Coordination-store client interface.

The reference binds its cache directly to zkstream (reference
``lib/zk.js:33-39``) — its biggest testability gap (SURVEY §4: every test
needs a live ZooKeeper).  The rebuild defines this narrow interface instead,
with two implementations:

- ``binder_tpu.store.fake.FakeStore`` — in-memory, synchronous; used by
  tests and ``bench.py``.
- ``binder_tpu.store.zk_client.ZKClient`` — real ZooKeeper wire protocol
  (jute) over asyncio.

Semantics modeled on zkstream's surface as consumed by the cache:

- The client emits a ``session`` event whenever a (new) session is
  established; the cache responds by re-binding its whole watch tree
  (reference ``lib/zk.js:45-47``).
- ``watcher(path)`` returns a ``Watcher`` handle.  Registering listeners is
  idempotent w.r.t. rebinds: the cache clears listeners and re-adds them on
  every rebind.  After (re)registration the store fires the current state —
  a ``children`` event with the current child names and a ``data`` event
  with the current node bytes — and again on every subsequent change.
- Watch events carry state, not deltas: ``children`` always delivers the
  full current child list.
"""
from __future__ import annotations

from typing import Callable, Dict, List


class Watcher:
    """Per-path watch handle: holds ``children`` and ``data`` listeners.

    Mirrors zkstream's watcher EventEmitter surface (``childrenChanged`` /
    ``dataChanged``) as used at reference ``lib/zk.js:215-219``.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        self._listeners: Dict[str, List[Callable]] = {"children": [], "data": []}

    def on(self, event: str, cb: Callable) -> None:
        self._listeners[event].append(cb)

    def clear(self) -> None:
        """Remove all listeners (reference removeAllListeners,
        ``lib/zk.js:211-214``)."""
        for lst in self._listeners.values():
            lst.clear()

    def emit(self, event: str, *args) -> None:
        for cb in list(self._listeners[event]):
            cb(*args)

    @property
    def has_listeners(self) -> bool:
        return any(self._listeners.values())


class StoreClient:
    """Abstract coordination-store client (zkstream-equivalent surface)."""

    def on_session(self, cb: Callable[[], None]) -> None:
        """Register a callback fired on every session (re-)establishment."""
        raise NotImplementedError

    def watcher(self, path: str) -> Watcher:
        """Return the watch handle for *path* (created on first use).

        After the caller attaches listeners, the store must deliver the
        current state of the node (children + data) and keep delivering on
        changes, for as long as the session lasts.
        """
        raise NotImplementedError

    def is_connected(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError
