"""Interned-name pool + compact node records: the million-name store
representation.

Every structure that touches a DNS name — the mirror's node index, the
reverse (PTR) map, the answer cache's dependency-tag index, the
compiled-answer table, the shard mutation log — used to hold its own
copy of the same strings, and every mirrored znode held a freshly
parsed JSON dict whose *keys* alone ("type", "host", "address")
dominated per-name RSS at scale (json.loads memoizes keys within one
document only; across a million parses each key exists a million
times).  "Parsing Millions of DNS Records per Second"
(arXiv:2411.12035) makes the general point: at record-set scale the
representation, not the parser, is what falls over.

Two tools, shared process-wide through the module-level :data:`POOL`:

- :class:`NamePool` — one canonical ``str``/``bytes`` object per
  label/name/tag.  Interning is a dict probe; a sweep pass (triggered
  by growth, refcount-based) drops names nothing references anymore,
  so a churning zone can't grow the pool without bound.
- ``compact_record`` / ``expand_record`` — the dominant znode shape
  (a host-like record: ``{"type": t, t: {"address": a}}`` with
  optional integer TTLs) collapses to a 4-tuple
  ``(rtype, address, ttl, sub_ttl)``; everything else keeps its parsed
  form with interned keys.  ``expand_record`` reconstructs an equal
  dict on demand (``TreeNode.data`` is a property), so every existing
  consumer — engine, zone pushes, shard snapshot frames — reads the
  same shape it always did, while hot paths read the tuple directly
  via ``TreeNode.rec``.

Measured (tools/zone_probe.py): the dict-per-node mirror cost
~2.1 KB/name at 100k names; the interned + compact representation is
the ≥5x cut ISSUE 7 requires.
"""
from __future__ import annotations

import sys
from typing import Optional, Tuple

#: compact record: (rtype, address, ttl, sub_ttl) — ttls None when the
#: record did not carry them (DEFAULT_TTL applies at resolve time)
CompactRec = Tuple[str, str, Optional[int], Optional[int]]

#: pool size below which the sweep never runs (tiny test zones)
_SWEEP_FLOOR = 4096


class NamePool:
    """Canonical-object pool for names, labels, and wire-format names.

    ``intern``/``intern_bytes`` return THE process-wide object for a
    value; callers drop their private copy on the floor.  Dead entries
    (nothing but the pool referencing them) are reclaimed by a sweep
    pass that runs opportunistically when the pool has doubled since
    the last sweep — amortized O(1) per intern, so the mutation path
    never pays a full pass at a bad time.
    """

    __slots__ = ("_strs", "_bytes", "hits", "sweeps", "_next_sweep")

    def __init__(self) -> None:
        self._strs: dict = {}
        self._bytes: dict = {}
        self.hits = 0
        self.sweeps = 0
        self._next_sweep = _SWEEP_FLOOR

    def intern(self, s: str) -> str:
        c = self._strs.get(s)
        if c is not None:
            self.hits += 1
            return c
        self._strs[s] = s
        if len(self._strs) + len(self._bytes) >= self._next_sweep:
            self.sweep()
        return s

    def intern_bytes(self, b: bytes) -> bytes:
        c = self._bytes.get(b)
        if c is not None:
            self.hits += 1
            return c
        self._bytes[b] = b
        if len(self._strs) + len(self._bytes) >= self._next_sweep:
            self.sweep()
        return b

    def sweep(self) -> int:
        """Drop entries nothing outside the pool references; returns
        how many were dropped.  A pooled value's refcount is 3 when
        only the pool holds it (dict key + dict value + the getrefcount
        argument), so anything above that is live somewhere."""
        getref = sys.getrefcount
        dropped = 0
        for pool in (self._strs, self._bytes):
            # key snapshot: an intern from another thread (a shard
            # replica's blocking snapshot reader) must not blow up the
            # sweep's iteration
            dead = [s for s in list(pool) if getref(s) <= 5]
            # <= 5: pool key + value + snapshot list + iteration
            # variable + the getrefcount argument
            for s in dead:
                pool.pop(s, None)
            dropped += len(dead)
        self.sweeps += 1
        self._next_sweep = max(_SWEEP_FLOOR,
                               2 * (len(self._strs) + len(self._bytes)))
        return dropped

    def __len__(self) -> int:
        return len(self._strs) + len(self._bytes)

    def stats(self) -> dict:
        return {
            "interned": len(self._strs) + len(self._bytes),
            "interned_str": len(self._strs),
            "interned_bytes": len(self._bytes),
            "hits": self.hits,
            "sweeps": self.sweeps,
        }


#: THE pool.  One per process on purpose: the mirror, the answer
#: cache's tag index, the compiled-answer table, and a shard worker's
#: replica feed all intern through here, which is what makes a name
#: ONE object no matter how many layers index it.
POOL = NamePool()

intern_name = POOL.intern
intern_wire = POOL.intern_bytes

#: keys a compactable record may carry, nothing else (an extra field
#: must survive round-trips verbatim, so records carrying one keep
#: their dict form)
_SUB_KEYS = frozenset(("address", "ttl"))

#: the record types that compact: exactly the host-like single-address
#: types (the canonical list, re-exported as ``store.cache.HOST_TYPES``).
#: Service/database/unknown types always keep their dict form so every
#: consumer branch that special-cases them sees the shape it expects.
HOST_TYPES = frozenset({
    "db_host", "host", "load_balancer", "moray_host",
    "redis_host", "ops_host", "rr_host",
})


def compact_record(parsed):
    """Compact a parsed znode value.  Host-like single-address records
    become a ``CompactRec`` tuple (a shape JSON can never produce, so
    ``type(rec) is tuple`` is an unambiguous representation marker);
    every other dict keeps its structure with interned keys; lists and
    None pass through."""
    if type(parsed) is not dict:
        return parsed
    rtype = parsed.get("type")
    if type(rtype) is str and rtype in HOST_TYPES:
        sub = parsed.get(rtype)
        if (type(sub) is dict and len(parsed) <= 3
                and type(sub.get("address")) is str
                and _SUB_KEYS.issuperset(sub)):
            ttl = parsed.get("ttl")
            sttl = sub.get("ttl")
            extra = len(parsed) - 2 - (ttl is not None)
            if (extra == 0 and (ttl is None or type(ttl) is int)
                    and (sttl is None or type(sttl) is int)):
                # the rtype recurs across the whole zone (intern); the
                # address is unique per host — pooling it would cost a
                # pool entry per name for zero dedup (the reverse map
                # shares this same object naturally).  The dominant
                # TTL-less shape packs to a 2-tuple.
                if ttl is None and sttl is None:
                    return (intern_name(rtype), sub["address"])
                return (intern_name(rtype), sub["address"], ttl, sttl)
    return _intern_keys(parsed)


def _intern_keys(obj):
    """Intern every dict key (and short ``type``-ish string values stay
    as-is — values are high-cardinality, keys are not) through the
    nested structure of a non-compactable record, in place where
    possible."""
    if type(obj) is dict:
        return {intern_name(k) if type(k) is str else k: _intern_keys(v)
                for k, v in obj.items()}
    if type(obj) is list:
        return [_intern_keys(v) for v in obj]
    if type(obj) is str and len(obj) <= 32:
        return intern_name(obj)
    return obj


def rec_parts(rec: tuple) -> CompactRec:
    """Uniform ``(rtype, address, ttl, sub_ttl)`` view of a compact
    record (the TTL-less shape is stored as a 2-tuple)."""
    if len(rec) == 4:
        return rec
    return (rec[0], rec[1], None, None)


def expand_record(rec):
    """The inverse of ``compact_record`` for the tuple form: rebuild an
    equal dict (``==`` to the original parse; key order is not part of
    the contract).  Non-tuples pass through untouched."""
    if type(rec) is not tuple:
        return rec
    rtype, addr, ttl, sttl = rec_parts(rec)
    sub = {"address": addr}
    if sttl is not None:
        sub["ttl"] = sttl
    out = {"type": rtype, rtype: sub}
    if ttl is not None:
        out["ttl"] = ttl
    return out
