"""Asyncio ZooKeeper client implementing the StoreClient interface.

The zkstream equivalent (reference ``lib/zk.js:33-39`` creates a zkstream
Client with a 30s session timeout and rebuilds its cache on every
``session`` event).  Speaks the public ZooKeeper 3.4 wire protocol
directly (see ``jute.py``); no external ZK library exists in this image.

Semantics:
- **Session loop**: connect → handshake (resuming the previous session id
  if any) → serve requests/watch events → on disconnect, reconnect with
  backoff.  A handshake that establishes a *new* session (first connect,
  or the old one expired) fires the ``session`` callbacks, which makes
  the mirror cache re-register its whole watch tree
  (``MirrorCache.rebuild``), exactly like the reference's full rebuild on
  zkstream's ``session`` event (``lib/zk.js:45-47,68-76``).  We
  conservatively fire ``session`` on *every* reconnect: ZK watches are
  not replayed for a resumed session unless re-registered, and re-issuing
  the read+watch pass is always safe (watch delivery is state-based here,
  events carry no payload).
- **Watches**: one-shot on the wire.  Attaching a listener to a Watcher
  triggers an async fetch (getChildren2/getData with watch=1, or an
  exists-watch for nodes that don't exist yet); each WatcherEvent
  re-issues the fetch, re-arming the watch and emitting fresh state to
  the cache (state, not deltas — same contract as FakeStore).
- **Ping**: every timeout/3 to keep the session alive.
- **Shared watches** (ROADMAP 3b): when the mirror offers its
  domain→node index via ``bind_source``, the client stops allocating a
  per-path ``_ZKWatcher`` (~190 B/znode) and stops registering two wire
  watches per znode.  Each bound node costs ONE getData(watch=1) whose
  trailing Stat says whether the node has children; the additional
  getChildren2(watch=1) goes only to nodes that have children now or
  could grow them — structural nodes (no record), container records
  (services), anything non-host — while host-record leaves, the ~30:1
  bulk of a production zone, stop at the data watch.  Watch events are
  dispatched straight through the mirror index (path → domain → node).
  At a million names this nearly halves both the server-side watch
  table and the session re-establishment chatter: a rebuild issues
  ~nodes + directories requests instead of 2×nodes.  Residual
  relaxation: a HOST-record leaf that gains a first child is only
  noticed at its next data touch or session rebuild — in this data
  model children hang off service records, which always keep a
  children watch.
"""
from __future__ import annotations

import asyncio
import logging
import struct
from typing import Callable, Dict, List, Optional

from binder_tpu.store import jute
from binder_tpu.store.interface import (SessionStateMixin, StoreClient,
                                        Watcher)
from binder_tpu.store.jute import Buf, Err, EventType, OpCode
from binder_tpu.utils.endpoints import parse_endpoint

RECONNECT_DELAY = 1.0
# Connect attempts must be bounded well under the session timeout: a
# blackholed ensemble member (SYNs dropped, no RST) would otherwise
# stall rotation for the kernel's ~2 min connect timeout while the
# session expires.
CONNECT_TIMEOUT = 3.0


class _ZKWatcher(Watcher):
    """Watcher whose listener attachment triggers a watched fetch."""

    __slots__ = ("_client",)

    def __init__(self, client: "ZKClient", path: str) -> None:
        super().__init__(path)
        self._client = client

    def on(self, event: str, cb: Callable) -> None:
        super().on(event, cb)
        self._client._schedule_sync(self.path, event)

    def bind_node(self, tn) -> None:
        super().bind_node(tn)
        self._client._schedule_sync(self.path, "children")
        self._client._schedule_sync(self.path, "data")


def parse_connect_string(address: str, default_port: int
                         ) -> List[tuple]:
    """``"h1,h2:2182,[::1]:2183"`` → ``[(h1, dp), (h2, 2182), (::1, 2183)]``.

    The multi-host connect string is standard ZooKeeper client surface
    (production binder co-locates with a 3-5 node ensemble,
    reference README.md:36-39); each entry may carry its own port."""
    servers = [parse_endpoint(entry, default_port)
               for entry in address.split(",") if entry.strip()]
    if not servers:
        raise ValueError(f"empty ZooKeeper connect string: {address!r}")
    return servers


class ZKClient(SessionStateMixin, StoreClient):
    def __init__(self, address: str = "127.0.0.1", port: int = 2181,
                 session_timeout_ms: int = 30000,
                 log: Optional[logging.Logger] = None,
                 collector=None, recorder=None) -> None:
        self._init_session_state(recorder)
        self.address = address
        self.port = port
        # ensemble rotation state: reconnects walk the server list round-
        # robin, so losing one server fails over to the next (the session,
        # replicated by ZAB, survives the move)
        self._servers = parse_connect_string(address, port)
        self._server_idx = 0
        self.session_timeout_ms = session_timeout_ms
        self.log = log or logging.getLogger("binder.zk")

        # client observability (zkstream publishes the analogous metrics
        # through the shared artedi collector, reference lib/zk.js:26-38)
        self.m_sessions = self.m_requests = self.m_notifications = None
        if collector is not None:
            self.m_sessions = collector.counter(
                "binder_zk_sessions_established",
                "ZooKeeper sessions established (1 + reconnects)").labelled()
            self.m_requests = collector.counter(
                "binder_zk_requests", "ZooKeeper requests sent").labelled()
            self.m_notifications = collector.counter(
                "binder_zk_watch_notifications",
                "ZooKeeper watch notifications received").labelled()
            collector.gauge(
                "binder_zk_connected",
                "1 while the ZooKeeper session is live"
            ).set_function(lambda: 1.0 if self._connected else 0.0)
            collector.gauge(
                "binder_zk_outstanding_requests",
                "requests awaiting a ZooKeeper response"
            ).set_function(lambda: len(self._pending))

        self._session_cbs: List[Callable[[], None]] = []
        self._watchers: Dict[str, _ZKWatcher] = {}
        # mirror's domain->node index once bind_source was accepted;
        # None keeps the legacy one-watcher-per-path mode (explicit
        # watcher() consumers — e.g. the federation registry — always
        # use that mode regardless)
        self._shared_nodes = None
        self._connected = False
        self._closed = False

        self._session_id = 0
        self._passwd = b"\x00" * 16
        self._negotiated_timeout = session_timeout_ms

        self._writer: Optional[asyncio.StreamWriter] = None
        self._xid = 0
        self._pending: Dict[int, asyncio.Future] = {}
        self._tasks: List[asyncio.Task] = []
        self._loop_task: Optional[asyncio.Task] = None
        # paths we watch via exists() because they don't exist yet
        self._exists_watch: set = set()

        try:
            asyncio.get_running_loop()
            self._loop_task = asyncio.ensure_future(self._session_loop())
        except RuntimeError:
            pass  # caller starts us with start()

    # -- StoreClient interface --

    def start(self) -> None:
        if self._loop_task is None:
            self._loop_task = asyncio.ensure_future(self._session_loop())

    def on_session(self, cb: Callable[[], None]) -> None:
        self._session_cbs.append(cb)
        if self._connected:
            cb()

    def watcher(self, path: str) -> Watcher:
        w = self._watchers.get(path)
        if w is None:
            w = _ZKWatcher(self, path)
            self._watchers[path] = w
        return w

    # -- shared-watch mode (mirror fast binding, see module docstring) --

    def bind_source(self, nodes) -> bool:
        """Accept the mirror's domain->node index: per-node binds then
        carry no per-node client state, and leaf znodes register one
        wire watch instead of two (the data watch; directory-ness comes
        from that request's trailing Stat)."""
        self._shared_nodes = nodes
        return True

    @staticmethod
    def _path_domain(path: str) -> str:
        """``/com/foo/web`` -> ``web.foo.com`` (inverse of
        ``cache.domain_to_path``)."""
        return ".".join(reversed([p for p in path.split("/") if p])).lower()

    def bind_node(self, path: str, node) -> None:
        if self._shared_nodes is None:
            StoreClient.bind_node(self, path, node)
            return
        self._schedule_shared(path, "bind")

    def unbind_node(self, path: str, node) -> None:
        if self._shared_nodes is None:
            StoreClient.unbind_node(self, path, node)
        # shared mode: nothing to tear down — the mirror already removed
        # the node from its index, so a later one-shot watch event for
        # the path dispatches to nothing and is dropped

    def is_connected(self) -> bool:
        """True only while a live session is established.  The bool
        cannot distinguish "never connected" from "session lost" — use
        ``session_state()`` (SessionStateMixin) for the full state
        machine and ``disconnected_seconds()`` for the exact, measured
        age of a loss."""
        return self._connected

    def close(self) -> None:
        self._session_transition("closed", "close() called")
        self._closed = True
        self._connected = False
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:  # noqa: BLE001
                pass
        for t in self._tasks + ([self._loop_task] if self._loop_task
                                else []):
            t.cancel()

    # -- session loop --

    async def _session_loop(self) -> None:
        while not self._closed:
            err = ""
            try:
                await self._run_session()
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001
                self.log.warning("zk: session error: %s", e)
                err = str(e)
            self._connected = False
            if self._session_state == "connected":
                # a live session just dropped: degraded until the
                # reconnect either resumes it or learns it expired
                self._session_transition("degraded", err or "disconnected")
            # whatever ended the session, try the next ensemble member
            # (reconnecting straight back to a dead server would burn a
            # full RECONNECT_DELAY cycle per retry)
            self._server_idx = (self._server_idx + 1) % len(self._servers)
            if self._closed:
                return
            await asyncio.sleep(RECONNECT_DELAY)

    async def _handshake(self, host: str, port: int):
        """Connect and exchange the ConnectRequest/Response.

        Runs under one CONNECT_TIMEOUT deadline (see _run_session): a
        half-alive ensemble member that accepts TCP but never answers the
        handshake must fail fast so server rotation can advance, instead
        of stalling the session loop on the response read forever.
        """
        reader, writer = await asyncio.open_connection(host, port)
        self._writer = writer
        try:
            # ConnectRequest: protoVer, lastZxidSeen, timeout, sessionId,
            # passwd (+ readOnly flag, 3.4+)
            req = (jute.i32(0) + jute.i64(0)
                   + jute.i32(self.session_timeout_ms)
                   + jute.i64(self._session_id)
                   + jute.buffer(self._passwd) + jute.boolean(False))
            writer.write(jute.frame(req))
            await writer.drain()
            resp = await self._read_frame(reader)
        except BaseException:
            self._writer = None
            writer.close()
            raise
        return reader, writer, resp

    async def _run_session(self) -> None:
        host, port = self._servers[self._server_idx]
        reader, writer, raw_resp = await asyncio.wait_for(
            self._handshake(host, port), CONNECT_TIMEOUT)
        try:
            resp = Buf(raw_resp)
            resp.i32()  # protocol version
            timeout = resp.i32()
            session_id = resp.i64()
            passwd = resp.buffer() or b"\x00" * 16
            if timeout <= 0 or session_id == 0:
                # session expired server-side: start a fresh one
                self.log.warning("zk: session expired; starting new session")
                self._session_transition("expired",
                                         "session expired server-side")
                self._session_id = 0
                self._passwd = b"\x00" * 16
                return
            self._session_id = session_id
            self._passwd = passwd
            self._negotiated_timeout = timeout
            self._connected = True
            self._session_transition(
                "connected", f"session 0x{session_id:x} via {host}:{port}")
            if self.m_sessions is not None:
                self.m_sessions.inc()
            self.log.info("zk: session 0x%x established (timeout %dms)",
                          session_id, timeout)

            ping_task = asyncio.ensure_future(self._ping_loop())
            self._tasks.append(ping_task)
            try:
                # fire session callbacks -> cache rebinds -> watched reads
                for cb in list(self._session_cbs):
                    cb()
                await self._read_loop(reader)
            finally:
                ping_task.cancel()
                self._tasks.remove(ping_task)
                for fut in self._pending.values():
                    if not fut.done():
                        fut.set_exception(ConnectionError("zk: disconnected"))
                self._pending.clear()
        finally:
            self._connected = False
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._writer = None

    async def _read_frame(self, reader: asyncio.StreamReader) -> bytes:
        hdr = await reader.readexactly(4)
        (length,) = struct.unpack(">i", hdr)
        if length < 0 or length > 4 * 1024 * 1024:
            raise ConnectionError(f"zk: bad frame length {length}")
        return await reader.readexactly(length)

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        # Dead-peer detection: our pings elicit replies every timeout/3,
        # so a full session timeout with no frame at all means the server
        # is gone even if TCP hasn't noticed (no FIN/RST on partition).
        read_timeout = max(1.0, self._negotiated_timeout / 1000.0)
        while True:
            try:
                frame = await asyncio.wait_for(self._read_frame(reader),
                                               timeout=read_timeout)
            except asyncio.TimeoutError:
                raise ConnectionError(
                    "zk: no traffic within session timeout; "
                    "assuming dead peer")
            buf = Buf(frame)
            xid = buf.i32()
            if xid == jute.XID_WATCHER_EVENT:
                buf.i64()  # zxid
                buf.i32()  # err
                etype = buf.i32()
                buf.i32()  # keeper state
                path = buf.string()
                self._on_watch_event(etype, path)
                continue
            if xid == jute.XID_PING:
                buf.i64()
                buf.i32()
                continue
            zxid = buf.i64()
            err = buf.i32()
            fut = self._pending.pop(xid, None)
            if fut is not None and not fut.done():
                fut.set_result((err, buf))

    async def _ping_loop(self) -> None:
        interval = max(0.5, self._negotiated_timeout / 3000.0)
        while True:
            await asyncio.sleep(interval)
            self._send(jute.XID_PING, OpCode.PING, b"")

    # -- request plumbing --

    def _send(self, xid: int, opcode: int, body: bytes) -> None:
        if self._writer is None:
            raise ConnectionError("zk: not connected")
        self._writer.write(jute.frame(jute.i32(xid) + jute.i32(opcode)
                                      + body))

    async def _call(self, opcode: int, body: bytes):
        self._xid += 1
        xid = self._xid
        fut = asyncio.get_running_loop().create_future()
        self._pending[xid] = fut
        if self.m_requests is not None:
            self.m_requests.inc()
        self._send(xid, opcode, body)
        return await fut

    # -- public reads (used by the sync machinery and tests) --

    async def get_children(self, path: str,
                           watch: bool = False) -> Optional[List[str]]:
        err, buf = await self._call(OpCode.GETCHILDREN2,
                                    jute.string(path) + jute.boolean(watch))
        if err == Err.NONODE:
            return None
        if err != Err.OK:
            raise ConnectionError(f"zk: getChildren({path}) err {err}")
        n = buf.i32()
        return sorted(buf.string() for _ in range(max(0, n)))

    async def get_data(self, path: str,
                       watch: bool = False) -> Optional[bytes]:
        err, buf = await self._call(OpCode.GETDATA,
                                    jute.string(path) + jute.boolean(watch))
        if err == Err.NONODE:
            return None
        if err != Err.OK:
            raise ConnectionError(f"zk: getData({path}) err {err}")
        return buf.buffer() or b""

    async def get_data2(self, path: str, watch: bool = False):
        """getData returning ``(data, stat_dict)`` instead of discarding
        the trailing Stat — its ``numChildren`` is how the shared-watch
        sync learns directory-ness without a getChildren round trip.
        None when the node does not exist."""
        err, buf = await self._call(OpCode.GETDATA,
                                    jute.string(path) + jute.boolean(watch))
        if err == Err.NONODE:
            return None
        if err != Err.OK:
            raise ConnectionError(f"zk: getData({path}) err {err}")
        data = buf.buffer() or b""
        return data, jute.read_stat(buf)

    async def exists(self, path: str, watch: bool = False) -> bool:
        err, buf = await self._call(OpCode.EXISTS,
                                    jute.string(path) + jute.boolean(watch))
        return err == Err.OK

    # -- writes (registrar-equivalent surface; used by tests/tools) --

    async def create(self, path: str, data: bytes = b"") -> None:
        body = (jute.string(path) + jute.buffer(data)
                + jute.i32(1)          # one ACL
                + jute.i32(31) + jute.string("world") + jute.string("anyone")
                + jute.i32(0))         # flags: persistent
        err, _ = await self._call(OpCode.CREATE, body)
        if err not in (Err.OK, Err.NODEEXISTS):
            raise ConnectionError(f"zk: create({path}) err {err}")

    async def set_data(self, path: str, data: bytes) -> None:
        err, _ = await self._call(OpCode.SETDATA, jute.string(path)
                                  + jute.buffer(data) + jute.i32(-1))
        if err != Err.OK:
            raise ConnectionError(f"zk: setData({path}) err {err}")

    async def delete(self, path: str) -> None:
        err, _ = await self._call(OpCode.DELETE,
                                  jute.string(path) + jute.i32(-1))
        if err not in (Err.OK, Err.NONODE):
            raise ConnectionError(f"zk: delete({path}) err {err}")

    async def mkdirp(self, path: str, data: bytes = b"") -> None:
        parts = [p for p in path.split("/") if p]
        cur = ""
        for i, p in enumerate(parts):
            cur += "/" + p
            await self.create(cur, data if i == len(parts) - 1 else b"")
        if data and await self.get_data(path) != data:
            await self.set_data(path, data)

    # -- watch/sync machinery --

    def _schedule_sync(self, path: str, event: str) -> None:
        if not self._connected:
            return  # the session callback will rebind + resync everything
        task = asyncio.ensure_future(self._sync(path, event))
        self._tasks.append(task)
        task.add_done_callback(self._tasks.remove)

    async def _sync(self, path: str, event: str) -> None:
        """Fetch current state with a fresh watch and emit it."""
        w = self._watchers.get(path)
        if w is None or not w.has_listeners:
            return
        try:
            if event == "children":
                kids = await self.get_children(path, watch=True)
                if kids is None:
                    await self._arm_exists_watch(path)
                    return
                w.emit("children", kids)
            elif event == "data":
                data = await self.get_data(path, watch=True)
                if data is None:
                    await self._arm_exists_watch(path)
                    return
                w.emit("data", data)
        except (ConnectionError, asyncio.CancelledError):
            pass  # reconnect path will resync

    # -- shared-watch sync (mirror-bound paths, no per-path watcher) --

    def _schedule_shared(self, path: str, want: str) -> None:
        if not self._connected or self._shared_nodes is None:
            return  # the session callback will rebind + resync everything
        task = asyncio.ensure_future(self._sync_shared(path, want))
        self._tasks.append(task)
        task.add_done_callback(self._tasks.remove)

    def _shared_node(self, path: str):
        nodes = self._shared_nodes
        if nodes is None:
            return None
        return nodes.get(self._path_domain(path))

    async def _sync_shared(self, path: str, want: str) -> None:
        """Fetch current state with fresh watches and deliver it to the
        mirror node the path maps to (dropped if it was unbound since).

        ``bind`` is the full pass: one watched getData whose Stat
        decides whether a watched getChildren follows — only for nodes
        that have children now, or whose record is a container type
        (dict-shaped, e.g. a service) and so may grow children later.
        Host leaves — the million-name bulk — stop at the data watch.
        """
        node = self._shared_node(path)
        if node is None:
            return
        try:
            if want == "children":
                kids = await self.get_children(path, watch=True)
                if kids is None:
                    await self._arm_exists_watch(path)
                    return
                node.on_children_changed(kids)
                return
            res = await self.get_data2(path, watch=True)
            if res is None:
                await self._arm_exists_watch(path)
                return
            data, stat = res
            node.on_data_changed(data)
            # Children watch for every node EXCEPT host-record leaves
            # (compact tuples — the ~30:1 bulk of a production zone).
            # Structural nodes (no record: the mirror root and interior
            # path components) and container records (dict-shaped, e.g.
            # services) may grow children at any time, so they keep the
            # watch even while childless; a host leaf that somehow has
            # children is caught by the Stat.  On a plain data touch
            # the Stat doubles as a heal: children that appeared while
            # a node was watch-less get picked up here.
            if stat["numChildren"] > 0 or type(node.rec) is not tuple:
                kids = await self.get_children(path, watch=True)
                if kids is not None:
                    node.on_children_changed(kids)
        except (ConnectionError, asyncio.CancelledError):
            pass  # reconnect path will resync

    async def _arm_exists_watch(self, path: str) -> None:
        if path in self._exists_watch:
            return
        self._exists_watch.add(path)
        try:
            if await self.exists(path, watch=True):
                # created between the NONODE and the exists call
                self._exists_watch.discard(path)
                self._resync_created(path)
        except (ConnectionError, asyncio.CancelledError):
            self._exists_watch.discard(path)

    def _resync_created(self, path: str) -> None:
        """A watched path (re)appeared: schedule the full fetch through
        whichever binding mode covers it.  Both schedules are cheap
        no-op tasks when the path has no listener of that kind."""
        self._schedule_sync(path, "children")
        self._schedule_sync(path, "data")
        self._schedule_shared(path, "bind")

    def _on_watch_event(self, etype: int, path: str) -> None:
        if self.m_notifications is not None:
            self.m_notifications.inc()
        self._exists_watch.discard(path)
        if etype == EventType.CREATED:
            self._resync_created(path)
        elif etype == EventType.DATA_CHANGED:
            self._schedule_sync(path, "data")
            self._schedule_shared(path, "data")
        elif etype == EventType.CHILDREN_CHANGED:
            self._schedule_sync(path, "children")
            self._schedule_shared(path, "children")
        elif etype == EventType.DELETED:
            # parent's children watch drives the unbind; re-arm creation
            # for paths something still listens on (for shared mode
            # that's a node still in the mirror index — notably the
            # mirror ROOT, which has no watched parent to notice its
            # re-creation)
            wants = ((path in self._watchers
                      and self._watchers[path].has_listeners)
                     or self._shared_node(path) is not None)
            if wants:
                task = asyncio.ensure_future(self._arm_exists_watch(path))
                self._tasks.append(task)
                task.add_done_callback(self._tasks.remove)
