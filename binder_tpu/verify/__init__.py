"""Serving-plane verification: incremental invariant checking +
mutation-to-glass propagation tracing (ISSUE 16).

``Verifier`` (checker.py) re-verifies only what each mutation can
affect, off the same invalidation feed the precompiler drains, with a
sampled time-budgeted background audit for drift the delta feed cannot
see.  ``PropagationTracer`` (tracer.py) stamps each mutation with a
trace context at the store event and folds per-stage latencies into
``binder_propagation_seconds``.
"""
from binder_tpu.verify.checker import INVARIANTS, Verifier
from binder_tpu.verify.tracer import STAGES, PropagationTracer

__all__ = ["INVARIANTS", "STAGES", "PropagationTracer", "Verifier"]
