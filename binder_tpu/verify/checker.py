"""Incremental serving-plane invariant checker (ISSUE 16, tentpole).

Janus-style (arXiv:2511.02559) incremental verification: instead of
re-proving the whole zone after every change, the checker hangs off the
SAME per-name invalidation feed the precompiler drains
(``MirrorCache.invalidate`` → ``BinderServer._on_store_invalidate``)
and re-verifies only what a mutation can have affected.  Invariants:

- ``dangling-srv``: every child label a service node advertises
  resolves to a live mirrored node (an SRV answer never names a target
  that left the tree);
- ``ptr-coherence``: the v4/v6 reverse maps and the forward records
  agree in both directions — a host-like node's address has a reverse
  entry that points back at a node carrying that address, and no
  reverse entry maps an address its node no longer owns;
- ``compiled-bytes``: a compiled-table entry's wires are byte-identical
  to a fresh engine render of the same plan (id 0 / RD clear are the
  canonical form on both sides; rotation variants compare in their
  deterministic order).  Only checked while the degradation policy is
  ``fresh`` — stale serving clamps TTLs in the rendered bytes;
- ``replica-digest``: shard replicas apply the same mutation log the
  owner sent, proven by rolling per-generation digest frames (see
  ``shard/protocol.delta_digest``; the supervisor/replica own the
  wire halves, violations are counted under this invariant on both
  sides);
- ``stale-epoch``: no pre-transition epoch survives a
  degradation-policy flush — after an ``invalidate_all`` the checker
  sweeps the compiled table (time-budgeted), and any old-epoch entry
  found AFTER the sweep completed is a violation (the bug class where
  a re-render captures its epoch before a flush and installs after).

Violations surface three ways at once: a ``verify-violation`` flight
event, the ``binder_verify_violations_total{invariant}`` counter, and
the ``recent_violations`` table in ``/status verify``.  Work the
checker cannot do soundly (stale mode, store not ready, queue
overflow) is counted as ``binder_verify_skipped_total`` — silence is
never ambiguous.

Everything is time-budgeted at 2 ms per event-loop pass (the PR 7
chunked-rebuild discipline), including the sampled full-zone
background audit that catches drift the delta feed cannot see —
corruption injected directly into tables (chaos ``corrupt-answer`` /
``drop-reverse``) never fires an invalidation, so only the audit walk
finds it.
"""
from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Optional

from binder_tpu.dns.wire import Rcode, Type, ip_from_reverse_name
from binder_tpu.resolver.answer_cache import _COMPILED
from binder_tpu.verify.tracer import PropagationTracer

#: the invariant catalog — the ``{invariant=...}`` label values of the
#: ``binder_verify_*_total`` families, all zero-seeded at startup and
#: pinned by ``tools/lint.py validate_verify_metrics``
INVARIANTS = (
    "dangling-srv",
    "ptr-coherence",
    "compiled-bytes",
    "replica-digest",
    "stale-epoch",
)

#: skip accounting for delta work shed under queue pressure (a series
#: on the skipped counter beside the per-invariant pins)
QUEUE_SHED = "queue-shed"


class Verifier:
    """The serving-plane checker: delta-fed incremental checks plus a
    sampled, budgeted background audit, and the owner of the process's
    :class:`~binder_tpu.verify.tracer.PropagationTracer`."""

    #: per-pass wall budget for the delta drain, the epoch sweep and
    #: each audit slice — same discipline as the chunked mirror rebuild
    BUDGET_S = 0.002
    MIN_CHUNK = 1
    #: delta-queue bound: overflow degrades to the audit (counted as
    #: skipped), never to unbounded memory
    MAX_QUEUE = 8192
    #: violations retained for the /status table
    RECENT_VIOLATIONS = 16

    def __init__(self, *, zk_cache, answer_cache=None, resolver=None,
                 precompiler=None, policy_mode=None, config=None,
                 collector=None, recorder=None,
                 log: Optional[logging.Logger] = None) -> None:
        cfg = dict(config or {})
        self.zk_cache = zk_cache
        self.answer_cache = answer_cache
        self.resolver = resolver
        self.precompiler = precompiler
        self._policy_mode = policy_mode or (lambda: "fresh")
        self.recorder = recorder
        self.log = log or logging.getLogger("binder.verify")
        self.audit_interval_s = float(
            cfg.get("auditIntervalSeconds", 0.25))
        #: check every Nth name/entry per audit pass; successive passes
        #: rotate the residue so N passes cover the whole zone
        self.audit_sample = max(1, int(cfg.get("auditSample", 1)))
        self.tracer = PropagationTracer(collector=collector,
                                        log=self.log)
        # plain dict mirrors of the counters for introspect() (and for
        # collector-less test builds)
        self.checks = {inv: 0 for inv in INVARIANTS}
        self.violations = {inv: 0 for inv in INVARIANTS}
        self.skipped = {inv: 0 for inv in INVARIANTS}
        self.skipped[QUEUE_SHED] = 0
        self.recent_violations: deque = deque(
            maxlen=self.RECENT_VIOLATIONS)
        self.audit_passes = 0
        # delta queue: insertion-ordered tag set (dict keys)
        self._queue: dict = {}
        self._drain_scheduled = False
        # stale-epoch sweep state (see _maybe_epoch_sweep)
        self._epoch_seen = zk_cache.epoch
        self._sweep_keys: list = []
        self._sweep_done = True
        # audit cursor
        self._audit_work: list = []
        self._audit_residue = 0
        self._audit_task = None
        self._m_checks = self._m_violations = self._m_skipped = None
        if collector is not None:
            checks = collector.counter(
                "binder_verify_checks_total",
                "serving-plane invariant checks evaluated")
            violations = collector.counter(
                "binder_verify_violations_total",
                "serving-plane invariant violations detected")
            skipped = collector.counter(
                "binder_verify_skipped_total",
                "invariant checks skipped (unsound mode, store not "
                "ready, or delta-queue overflow)")
            self._m_checks = {
                inv: checks.labelled({"invariant": inv})
                for inv in INVARIANTS}
            self._m_violations = {
                inv: violations.labelled({"invariant": inv})
                for inv in INVARIANTS}
            self._m_skipped = {
                inv: skipped.labelled({"invariant": inv})
                for inv in (INVARIANTS + (QUEUE_SHED,))}
            for children in (self._m_checks, self._m_violations,
                             self._m_skipped):
                for child in children.values():
                    child.inc(0)
            collector.gauge(
                "binder_verify_queue_depth",
                "invalidation tags awaiting incremental verification"
            ).set_function(lambda: float(len(self._queue)))

    # -- accounting --

    def _check(self, invariant: str, n: int = 1) -> None:
        self.checks[invariant] += n
        if self._m_checks is not None:
            self._m_checks[invariant].inc(n)

    def _skip(self, invariant: str, n: int = 1) -> None:
        self.skipped[invariant] += n
        if self._m_skipped is not None:
            self._m_skipped[invariant].inc(n)

    def _violation(self, invariant: str, **detail) -> None:
        self.violations[invariant] += 1
        if self._m_violations is not None:
            self._m_violations[invariant].inc()
        if self.recorder is not None:
            self.recorder.record("verify-violation",
                                 invariant=invariant, **detail)
        self.recent_violations.append(
            {"invariant": invariant, "at": time.time(), **detail})
        self.log.error("verify violation [%s]: %s", invariant, detail)

    def note_digest(self, gen: int, ok: bool, have=None,
                    want=None) -> None:
        """Fold a replica-digest comparison outcome (the shard replica
        compares on the wire; this is its counting/reporting sink)."""
        self._check("replica-digest")
        if not ok:
            self._violation("replica-digest", generation=gen,
                            have=have, want=want)

    # -- delta intake (BinderServer._on_store_invalidate) --

    def enqueue_tags(self, tags) -> None:
        q = self._queue
        room = self.MAX_QUEUE - len(q)
        shed = 0
        for tag in tags:
            if tag in q:
                continue
            if room <= 0:
                shed += 1
                continue
            q[tag] = None
            room -= 1
        if shed:
            self._skip(QUEUE_SHED, shed)
        self._schedule()

    def _schedule(self) -> None:
        if self._drain_scheduled or not self._queue:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # no loop (synchronous stores, tests): drain inline
            while self._queue or not self._sweep_done:
                self._drain(reschedule=False)
            return
        self._drain_scheduled = True
        loop.call_soon(self._drain)

    def _drain(self, reschedule: bool = True) -> None:
        self._drain_scheduled = False
        t0 = time.perf_counter()
        self._maybe_epoch_sweep(t0)
        n = 0
        q = self._queue
        while q:
            tag = next(iter(q))
            del q[tag]
            try:
                self._check_tag(tag)
            except Exception:  # noqa: BLE001 — verification must never
                self.log.exception(      # break the mutation path
                    "verify check failed for tag %s", tag)
            n += 1
            if (n >= self.MIN_CHUNK
                    and time.perf_counter() - t0 >= self.BUDGET_S):
                break
        if reschedule and (q or not self._sweep_done):
            self._drain_scheduled = False
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return
            self._drain_scheduled = True
            loop.call_soon(self._drain)

    # -- per-tag incremental checks --

    def _check_tag(self, tag: str) -> None:
        ip = ip_from_reverse_name(tag) \
            if tag.endswith((".in-addr.arpa", ".ip6.arpa")) else None
        if ip is not None:
            self._check_reverse_entry(ip)
        else:
            node = self.zk_cache.nodes.get(tag)
            if node is not None:
                self._check_node(node)
        self._check_compiled_for_tag(tag)

    def _check_reverse_entry(self, ip: str) -> None:
        """One reverse-map entry's coherence: if the map still claims
        *ip*, the claiming node must be live and still own the
        address."""
        self._check("ptr-coherence")
        node = self.zk_cache.rev_lookup.get(ip)
        if node is None:
            return                      # entry gone: nothing to claim
        if self.zk_cache.nodes.get(node.domain) is not node:
            self._violation("ptr-coherence", ip=ip, node=node.domain,
                            detail="reverse entry names an unmirrored "
                                   "node")
        elif node.ip != ip:
            self._violation("ptr-coherence", ip=ip, node=node.domain,
                            detail="reverse entry address mismatch")

    def _check_node(self, node) -> None:
        """Forward checks for one mirrored node: its address must be
        reachable through the reverse map, and — for service nodes —
        every advertised child label must resolve."""
        ip = node.ip
        if ip:
            self._check("ptr-coherence")
            rnode = self.zk_cache.rev_lookup.get(ip)
            if rnode is None:
                self._violation("ptr-coherence", ip=ip,
                                node=node.domain,
                                detail="host address missing from the "
                                       "reverse map")
            elif rnode.ip != ip:
                self._violation("ptr-coherence", ip=ip,
                                node=rnode.domain,
                                detail="reverse entry address mismatch")
        rec = node.rec
        rtype = rec[0] if type(rec) is tuple else (
            rec.get("type") if isinstance(rec, dict) else None)
        if rtype == "service" and node.kids:
            self._check("dangling-srv")
            nodes = self.zk_cache.nodes
            for label in node.kids:
                kid = (label + "." + node.domain).lower()
                if nodes.get(kid) is None:
                    self._violation("dangling-srv", service=node.domain,
                                    target=kid)

    # -- compiled-table checks --

    def _check_compiled_for_tag(self, tag: str) -> None:
        ac = self.answer_cache
        if ac is None:
            return
        keys = ac._by_tag.get(tag)
        if not keys:
            return
        for key in list(keys):
            if type(key) is tuple and len(key) == 3 \
                    and key[0] is _COMPILED:
                self._check_compiled(key[1:])

    def _check_compiled(self, ckey) -> None:
        ac = self.answer_cache
        e = ac._compiled.get(ckey)
        if e is None:
            return
        epoch = self.zk_cache.epoch
        self._check("stale-epoch")
        if e[0] != epoch:
            # during the post-flush sweep window old-epoch entries are
            # EXPECTED (the flush invalidated them wholesale) — purge;
            # after the sweep declared the table clean, survival is the
            # violation
            if self._sweep_done:
                self._violation("stale-epoch", qname=ckey[1],
                                qtype=ckey[0], entry_epoch=e[0],
                                epoch=epoch)
            ac._drop_compiled(ckey, e)
            return
        if self._policy_mode() != "fresh":
            # stale serving clamps TTLs in the rendered bytes: a
            # re-render would false-positive against a fresh-rendered
            # entry (and vice versa)
            self._skip("compiled-bytes")
            return
        pc, rz = self.precompiler, self.resolver
        if pc is None or rz is None:
            self._skip("compiled-bytes")
            return
        qtype, qname = ckey
        if qtype == Type.PTR:
            plan = rz.plan_ptr(qname)
        else:
            plan = rz.plan(qname, qtype)
        self._check("compiled-bytes")
        if plan.rcode == Rcode.SERVFAIL:
            self._skip("compiled-bytes")
            return
        if plan.miss:
            self._violation("compiled-bytes", qname=qname, qtype=qtype,
                            detail="compiled entry for a missing name")
            return
        fresh = pc.render_variants(qname, qtype, plan)
        if fresh is None:
            self._skip("compiled-bytes")  # oversize/unencodable: lazy
            return
        have = e[2]
        if len(fresh) != len(have):
            self._violation("compiled-bytes", qname=qname, qtype=qtype,
                            detail="variant count %d != fresh %d"
                                   % (len(have), len(fresh)))
            return
        for i, (hv, fv) in enumerate(zip(have, fresh)):
            if hv[0] != fv[0] or hv[1] != fv[1]:
                self._violation(
                    "compiled-bytes", qname=qname, qtype=qtype,
                    variant=i,
                    detail="compiled wire differs from fresh render")
                return

    # -- stale-epoch sweep --

    def _maybe_epoch_sweep(self, t0: float) -> None:
        ac = self.answer_cache
        if ac is None:
            return
        epoch = self.zk_cache.epoch
        if epoch != self._epoch_seen:
            self._epoch_seen = epoch
            self._sweep_keys = list(ac._compiled)
            self._sweep_done = not self._sweep_keys
        if self._sweep_done:
            return
        keys = self._sweep_keys
        while keys:
            ckey = keys.pop()
            e = ac._compiled.get(ckey)
            if e is not None and e[0] != epoch:
                self._check("stale-epoch")
                ac._drop_compiled(ckey, e)
            if time.perf_counter() - t0 >= self.BUDGET_S:
                return
        self._sweep_done = True

    # -- the sampled background audit --

    def start(self, loop) -> None:
        if self._audit_task is None:
            self._audit_task = loop.create_task(self._audit_loop())

    async def stop(self) -> None:
        task, self._audit_task = self._audit_task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _audit_loop(self) -> None:
        while True:
            await asyncio.sleep(self.audit_interval_s)
            try:
                self.audit_slice()
            except Exception:  # noqa: BLE001 — the audit must outlive
                self.log.exception("verify audit slice failed")

    def _audit_refill(self) -> None:
        """Snapshot the next pass's work list.  At zone scale the
        snapshot itself is the expensive step (one list() over the node
        index); it runs once per full cycle, stays an order of
        magnitude under the loop-lag watchdog at a million names, and
        the sample knob divides everything after it.  Residue rotation
        makes ``auditSample`` passes cover the whole zone."""
        n = self.audit_sample
        r = self._audit_residue
        self._audit_residue = (r + 1) % n
        zk = self.zk_cache
        work = [("name", d) for d in list(zk.nodes)[r::n]]
        work += [("rev", ip) for ip in list(zk.rev_lookup)[r::n]]
        if self.answer_cache is not None:
            work += [("ckey", k)
                     for k in list(self.answer_cache._compiled)[r::n]]
        self._audit_work = work
        self.audit_passes += 1

    def audit_slice(self) -> None:
        """One time-budgeted audit slice: resumes the in-flight pass or
        snapshots a new one.  Synchronous — tests drive it directly."""
        t0 = time.perf_counter()
        self._maybe_epoch_sweep(t0)
        if not self._audit_work:
            self._audit_refill()
        work = self._audit_work
        n = 0
        while work:
            kind, item = work.pop()
            try:
                if kind == "name":
                    node = self.zk_cache.nodes.get(item)
                    if node is not None:
                        self._check_node(node)
                elif kind == "rev":
                    self._check_reverse_entry(item)
                else:
                    self._check_compiled(item)
            except Exception:  # noqa: BLE001 — see _drain
                self.log.exception("verify audit failed for %s %s",
                                   kind, item)
            n += 1
            if (n >= self.MIN_CHUNK
                    and time.perf_counter() - t0 >= self.BUDGET_S):
                return

    def audit_cycle(self, max_slices: int = 10000) -> None:
        """Drive audit slices until one full pass completes (tests and
        the smoke harness — detection latency bounded by ONE cycle)."""
        if not self._audit_work:
            self.audit_slice()
        n = 0
        while self._audit_work and n < max_slices:
            self.audit_slice()
            n += 1

    # -- introspection (/status `verify` section) --

    def introspect(self) -> dict:
        return {
            "enabled": True,
            "checks": dict(self.checks),
            "violations": dict(self.violations),
            "skipped": dict(self.skipped),
            "queue_depth": len(self._queue),
            "audit": {
                "passes": self.audit_passes,
                "pending": len(self._audit_work),
                "interval_seconds": self.audit_interval_s,
                "sample": self.audit_sample,
            },
            "recent_violations": list(self.recent_violations),
            "propagation": self.tracer.introspect(),
        }
