"""Mutation-to-glass propagation tracing (ISSUE 16, tentpole half b).

Every mirrored mutation gets a trace context — ``(trace_id, t0)``,
stamped when the owner mirror bumps its generation for the store event
— and each datapath stage that touches the resulting answer observes
the elapsed time against that SAME t0:

- ``mirror-apply``: the owner mirror's invalidation fan-out fired;
- ``shard-frame``: the supervisor put the delta on a worker's
  mutation-log stream;
- ``replica-apply``: a worker's replica store applied the delta (the
  frame carries the owner's trace id and t0 — ``time.monotonic`` is
  CLOCK_MONOTONIC on Linux, comparable across processes on one box);
- ``precompile-render`` / ``compiled-install``: the precompiler
  re-rendered the affected answers and installed them in the compiled
  table;
- ``native-install``: the zone lane re-installed the answer in the
  native fast path.

Observations fold into the per-stage ``binder_propagation_seconds``
histogram plus bounded in-memory reservoirs for the ``/status verify``
section: per-stage p50/p99 and a slowest-recent table that names the
trace (so an operator can grep the flight recorder / logs for the
mutation behind a propagation outlier).  Stage latencies are
END-TO-END from the store event, not per-hop deltas: "how long until
the glass showed it" is the quantity the DNS Push lane needs, and the
stage ordering recovers the per-hop costs by subtraction.

The tracer is passive: with no mutations in flight every hook is a
couple of attribute reads, and it is never on the query path at all.
"""
from __future__ import annotations

import logging
import os
import time
from collections import deque
from itertools import count
from typing import Optional, Tuple

#: the datapath stages a mutation's trace can light up, in order — the
#: exposed ``binder_propagation_seconds{stage=...}`` series set and the
#: label pins ``tools/lint.py validate_verify_metrics`` enforces
STAGES = (
    "mirror-apply",
    "shard-frame",
    "replica-apply",
    "precompile-render",
    "compiled-install",
    "native-install",
)

#: per-stage reservoir for the introspected p50/p99 (bounded; the
#: histogram keeps the unbounded account)
RECENT_PER_STAGE = 512
#: slowest-recent observations retained / shown in ``/status verify``
SLOWEST_KEEP = 64
SLOWEST_SHOW = 8


def _quantile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


class PropagationTracer:
    """Allocates trace contexts at store events and folds per-stage
    observations into metrics + bounded introspection reservoirs.

    One instance per process; the owner-side instance lives on the
    serving plane's :class:`~binder_tpu.verify.checker.Verifier` (the
    shard supervisor builds a bare one — it has no answer plane), and
    the mirror/precompiler/server reach it through duck-typed
    ``tracer`` attributes so every hook stays optional.
    """

    def __init__(self, *, collector=None,
                 log: Optional[logging.Logger] = None) -> None:
        self.log = log or logging.getLogger("binder.verify")
        # the trace context of the mutation currently being applied:
        # valid through the mirror's synchronous invalidation fan-out
        # (callbacks capture it for their async continuations)
        self.current: Optional[Tuple[str, float]] = None
        # a context handed down from an upstream process (a shard
        # replica's delta frame), consumed by the next store event
        self._inherit: Optional[Tuple[str, float]] = None
        self._seq = count()
        self._pid = os.getpid()
        self.observed = 0
        self._recent = {s: deque(maxlen=RECENT_PER_STAGE) for s in STAGES}
        self._slowest: deque = deque(maxlen=SLOWEST_KEEP)
        self._hist = None
        if collector is not None:
            from binder_tpu.metrics.collector import DEFAULT_STAGE_BUCKETS
            hist = collector.histogram(
                "binder_propagation_seconds",
                "mutation-to-glass propagation latency from the store "
                "event to each datapath stage",
                buckets=DEFAULT_STAGE_BUCKETS)
            # materialize every stage series at 0 — the validator pins
            # the full stage set's presence before the first mutation
            self._hist = {s: hist.labelled({"stage": s}) for s in STAGES}

    # -- context lifecycle --

    def on_store_event(self, gen: int) -> None:
        """A mirrored mutation landed (``MirrorCache.bump_gen``): open
        its trace context — fresh, or the one a replica frame handed
        down (so the worker-side stages report against the OWNER's
        t0)."""
        inh = self._inherit
        if inh is not None:
            self._inherit = None
            self.current = inh
            return
        self.current = (f"m{self._pid:x}-{next(self._seq):x}",
                        time.monotonic())

    def inherit(self, tr, t0) -> None:
        """Stage an upstream context for the store event about to be
        applied (shard replica: called per delta frame, before the
        apply fires ``bump_gen``)."""
        if isinstance(tr, str) and isinstance(t0, (int, float)):
            self._inherit = (tr, float(t0))

    def clear(self) -> None:
        self._inherit = None

    # -- stage observations --

    def on_mirror_applied(self) -> None:
        self.observe("mirror-apply")

    def observe(self, stage: str,
                ctx: Optional[Tuple[str, float]] = None) -> None:
        """Record *stage* reached for *ctx* (default: the in-flight
        mutation).  No-op without a context — stages fired outside a
        traced mutation (startup seeds, tests) cost two loads."""
        if ctx is None:
            ctx = self.current
        if ctx is None:
            return
        dt = time.monotonic() - ctx[1]
        if dt < 0.0:
            dt = 0.0                    # cross-process clock guard
        self.observed += 1
        hist = self._hist
        if hist is not None:
            child = hist.get(stage)
            if child is not None:
                child.observe(dt)
        recent = self._recent.get(stage)
        if recent is not None:
            recent.append(dt)
        slow = self._slowest
        if len(slow) < slow.maxlen or dt > min(s[2] for s in slow):
            slow.append((stage, ctx[0], dt, time.time()))

    # -- introspection (/status verify.propagation) --

    def introspect(self) -> dict:
        stages = {}
        for stage in STAGES:
            vals = sorted(self._recent[stage])
            stages[stage] = {
                "count": len(vals),
                "p50_seconds": round(_quantile(vals, 0.50), 6),
                "p99_seconds": round(_quantile(vals, 0.99), 6),
            }
        slowest = sorted(self._slowest, key=lambda s: -s[2])
        return {
            "observed": self.observed,
            "stages": stages,
            "slowest": [
                {"stage": s[0], "trace": s[1],
                 "seconds": round(s[2], 6), "at": s[3]}
                for s in slowest[:SLOWEST_SHOW]],
        }
