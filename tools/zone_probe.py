#!/usr/bin/env python3
"""Zone-scale probe: mirror RSS/build/mutation-latency at N names.

The measurement half of ISSUE 7's ``zone_scale`` axis, shared by the
bench (``bench_impl._bench_zone_scale`` runs one probe subprocess per
zone size so measurements never pollute each other's RSS), by ``make
zone-smoke`` (tools/zone_smoke.py), and by tests/test_zone_scale.py.

Builds a synthetic zone (``store.fake.populate_synthetic``) in a fake
store, mirrors it, wires the answer-cache + mutation-time precompiler
the way BinderServer does, and measures:

- store/mirror build wall time and RSS delta (→ bytes per name);
- single-name mutation → re-rendered compiled answer latency
  (p50/p99 over a sample spread across the zone), with a byte-parity
  check of every re-rendered wire against a fresh engine render;
- watch-storm recovery: a burst of mutations against served names,
  time until the precompile backlog drains (event-loop mode, so the
  bounded drain is what's being measured);
- chunked session rebuild: wall time, chunk count, the worst
  event-loop stall observed while it streamed, and proof that lookups
  kept serving mid-rebuild;
- interned-name pool stats.

Usage:  python tools/zone_probe.py <names> [mutations] [storm]
Prints one JSON line.
"""
import asyncio
import gc
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from binder_tpu.resolver.answer_cache import AnswerCache  # noqa: E402
from binder_tpu.resolver.engine import Resolver  # noqa: E402
from binder_tpu.resolver.precompile import Precompiler  # noqa: E402
from binder_tpu.dns.wire import Type  # noqa: E402
from binder_tpu.store import FakeStore, MirrorCache  # noqa: E402
from binder_tpu.store.fake import populate_synthetic  # noqa: E402
from binder_tpu.store.names import POOL  # noqa: E402

DOMAIN = "bench.zone"


def rss_kb() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


def host_path(i: int, racks: int) -> str:
    return f"/zone/bench/zs/r{i % racks:04d}/h{i:06d}"


def host_name(i: int, racks: int) -> str:
    return f"h{i:06d}.r{i % racks:04d}.zs.{DOMAIN}"


class Harness:
    """The answer-path wiring of BinderServer, minus transports: an
    AnswerCache + Resolver + Precompiler fed by the mirror's per-name
    invalidation events, so a store mutation exercises the REAL
    mirror → drop → re-render chain."""

    def __init__(self, cache: MirrorCache, cache_size: int = 65536):
        self.cache = cache
        self.answer_cache = AnswerCache(size=cache_size,
                                        compiled_size=cache_size,
                                        intern=cache.canon)
        self.resolver = Resolver(cache, dns_domain=DOMAIN)
        self.pc = Precompiler(resolver=self.resolver,
                              answer_cache=self.answer_cache,
                              zk_cache=cache, summarize=str)
        self.pc.MAX_PENDING_CAP = cache_size
        cache.on_invalidate(self._on_invalidate)

    def _on_invalidate(self, tags) -> None:
        dropped = []
        for tag in tags:
            self.answer_cache.invalidate_tag(tag, dropped=dropped)
        if dropped:
            self.pc.enqueue(dropped)

    def prime(self, qname: str) -> None:
        """Install serving evidence for a name (what a real query
        would do), so its mutations are eagerly re-rendered."""
        self.pc._compile_one((Type.A, qname),
                             evidence_at=time.monotonic())

    def compiled_wire(self, qname: str):
        hit = self.answer_cache.get_compiled(Type.A, qname,
                                             self.cache.epoch)
        return None if hit is None else hit[0][0]

    def engine_wire(self, qname: str):
        plan = self.resolver.plan(qname, Type.A)
        answers = [r for g in plan.groups for r in g[0]]
        adds = [r for g in plan.groups for r in g[1]]
        return Precompiler._render(qname, Type.A, plan, answers, adds,
                                   False)


def probe(n: int, mutations: int = 200, storm: int = 2000) -> dict:
    racks = max(1, min(1024, n // 512))
    out = {"names": n, "racks": racks}

    gc.collect()
    rss0 = rss_kb()
    t0 = time.perf_counter()
    store = FakeStore()
    populate_synthetic(store, DOMAIN, n, racks=racks)
    out["store_build_s"] = round(time.perf_counter() - t0, 3)
    gc.collect()
    rss1 = rss_kb()
    out["store_rss_kb"] = rss1 - rss0

    t0 = time.perf_counter()
    cache = MirrorCache(store, DOMAIN)
    store.start_session()
    out["mirror_build_s"] = round(time.perf_counter() - t0, 3)
    gc.collect()
    rss2 = rss_kb()
    out["mirror_rss_kb"] = rss2 - rss1
    out["mirror_rss_per_name_bytes"] = round(
        (rss2 - rss1) * 1024 / max(1, n), 1)
    out["mirror_nodes"] = len(cache.nodes)

    h = Harness(cache)

    # single-name mutation -> re-rendered answer, sampled across the
    # zone; inline (no loop), so the timing is the full synchronous
    # mirror -> invalidate -> re-render chain and nothing else
    step = max(1, n // max(1, mutations))
    sample = list(range(0, n, step))[:mutations]
    for i in sample:
        h.prime(host_name(i, racks))
    lat_us = []
    parity_failures = 0
    for j, i in enumerate(sample):
        addr = f"10.200.{(j >> 8) & 255}.{j & 255}"
        body = json.dumps({"type": "host",
                           "host": {"address": addr}}).encode()
        t0 = time.perf_counter()
        store.set_data(host_path(i, racks), body)
        lat_us.append((time.perf_counter() - t0) * 1e6)
        name = host_name(i, racks)
        cw = h.compiled_wire(name)
        if cw is None or cw != h.engine_wire(name):
            parity_failures += 1
    lat_us.sort()
    out["mutation_p50_us"] = round(lat_us[len(lat_us) // 2], 1)
    out["mutation_p99_us"] = round(
        lat_us[min(len(lat_us) - 1, int(len(lat_us) * 0.99))], 1)
    out["mutation_samples"] = len(sample)
    out["parity_failures"] = parity_failures

    # watch storm + chunked rebuild need a live event loop (the
    # bounded drains are the thing being measured)
    async def loop_phase():
        res = {}
        burst = min(storm, n)
        step_b = max(1, n // max(1, burst))
        burst_idx = list(range(0, n, step_b))[:burst]
        for i in burst_idx:
            h.prime(host_name(i, racks))
        t0 = time.perf_counter()
        for j, i in enumerate(burst_idx):
            store.set_data(
                host_path(i, racks),
                b'{"type": "host", "host": {"address": "10.201.%d.%d"}}'
                % ((j >> 8) & 255, j & 255))
        res["storm_mutate_s"] = round(time.perf_counter() - t0, 3)
        while h.pc._pending:
            await asyncio.sleep(0)
        res["storm_recovery_s"] = round(time.perf_counter() - t0, 3)
        res["storm_burst"] = len(burst_idx)
        res["storm_shed"] = h.pc.shed

        # chunked session rebuild: serving continues, loop stays live
        loop = asyncio.get_running_loop()
        stalls = {"max": 0.0}
        probe_name = host_name(burst_idx[0], racks)
        served = {"mid": 0, "miss": 0}
        done = {"v": False}

        async def sampler():
            while not done["v"]:
                t = loop.time()
                await asyncio.sleep(0.002)
                lag = loop.time() - t - 0.002
                if lag > stalls["max"]:
                    stalls["max"] = lag
                if cache.rebuild_pending():
                    if cache.lookup(probe_name) is not None:
                        served["mid"] += 1
                    else:
                        served["miss"] += 1

        task = asyncio.ensure_future(sampler())
        t0 = time.perf_counter()
        chunks0 = cache.rebuild_chunks
        store.expire_session()
        while cache.rebuild_pending():
            await asyncio.sleep(0.001)
        res["rebuild_s"] = round(time.perf_counter() - t0, 3)
        res["rebuild_chunks"] = cache.rebuild_chunks - chunks0
        done["v"] = True
        await task
        res["rebuild_max_loop_lag_ms"] = round(stalls["max"] * 1000, 2)
        res["rebuild_served_mid"] = served["mid"]
        res["rebuild_miss_mid"] = served["miss"]
        return res

    out.update(asyncio.run(loop_phase()))
    out["pool"] = POOL.stats()
    out["compiled"] = h.pc.compiled
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    n = int(argv[0]) if argv else 100000
    mutations = int(argv[1]) if len(argv) > 1 else 200
    storm = int(argv[2]) if len(argv) > 2 else 2000
    print(json.dumps(probe(n, mutations, storm)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
