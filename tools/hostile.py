#!/usr/bin/env python3
"""Adversarial multi-flow DNS load harness (the ZDNS-style client).

The bench's dnsblast is a *friendly* client: one source address, well-
formed queries, qids it waits on.  That is exactly the flood shape the
per-client admission limiter sheds, which is why the recursion-heavy
bench axes had to lift the limit in config (PR 8) — and why "binder
survives the open internet" was an unmeasured claim.  This harness is
the unfriendly one:

- **Many distinct client flows.**  Every flow is its own UDP socket
  bound to its own loopback source address (Linux accepts any
  127.0.0.0/8 address unconfigured), so each carries a distinct
  4-tuple: `SO_REUSEPORT` shard hashing spreads them like real
  clients, and per-client/per-prefix token buckets are exercised
  honestly instead of seeing one mega-client.
- **Configurable traffic mix** over six categories: realistic
  queries (`legit`), cache-missing random names (`random`), the
  malformed-frame corpus (`malformed`), EDNS edge cases (`edns`),
  oversized frames (`oversized`), and a spoofed-source flood
  (`spoof`) where flows sit in attacker prefixes distinct from the
  legit client's.
- **Per-category accounting**: answered / refused / formerr /
  slipped (TC=1, empty — the RRL slip) / dropped (no reply), so the
  server's shed-vs-refuse split is attributable from the client side
  and can be cross-checked against `binder_shed_total` /
  `binder_rrl_*`.

The malformed corpus generator here is the single source of the
checked-in corpus (`tests/data/malformed_corpus.bin`, regenerate with
``python tools/hostile.py --write-corpus <path>``): the fuzz-clean
guarantee in tests/test_hostile.py replays the same frames this
harness fires.

Synchronous by design (selectors, not asyncio): the harness is the
measurement instrument, and per-packet event-loop overhead would cap
the flood it can represent.  `hostile_smoke.py` and the bench drive it
from a thread next to a legit-traffic measurement loop.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import selectors
import socket
import struct
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from binder_tpu.dns.wire import Type, make_query  # noqa: E402

CATEGORIES = ("legit", "random", "malformed", "edns", "oversized",
              "spoof")

#: default mix (fractions; normalized at parse time)
DEFAULT_MIX = {"legit": 0.25, "random": 0.20, "malformed": 0.15,
               "edns": 0.10, "oversized": 0.05, "spoof": 0.25}

#: realistic qtype distribution for the legit/spoof categories
QTYPE_MIX = ((Type.A, 70), (Type.AAAA, 15), (Type.SRV, 10),
             (Type.TXT, 3), (Type.PTR, 2))

#: loopback /24s the harness draws source addresses from.  The legit
#: measurement client lives at 127.0.0.1 (prefix 127.0.0/24); hostile
#: flows deliberately live elsewhere so per-prefix RRL isolates them.
HOSTILE_PREFIXES = ("127.66.7", "127.66.8", "127.99.1", "127.99.2")

CORPUS_DEFAULT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              os.pardir, "tests", "data",
                              "malformed_corpus.bin")


# ---------------------------------------------------------------------------
# Malformed-frame corpus (deterministic; the checked-in corpus is this)


def malformed_frames(seed: int = 1337) -> List[Tuple[str, bytes]]:
    """Deterministic (label, frame) corpus of malformed DNS wires.

    Every frame here must produce FORMERR-or-drop on every serve lane —
    never an exception, never a cache/precompile deposit.  Structured
    cases first (one per decoder failure mode), then seeded random fuzz
    for the failure modes nobody thought to enumerate."""
    out: List[Tuple[str, bytes]] = []
    hdr = struct.pack(">HHHHHH", 0x1234, 0x0100, 1, 0, 0, 0)

    def q(name_wire: bytes, tail: bytes = b"\x00\x01\x00\x01") -> bytes:
        return hdr + name_wire + tail

    out.append(("empty", b""))
    out.append(("one-byte", b"\x00"))
    out.append(("truncated-header", hdr[:11]))
    out.append(("header-only-but-counts", hdr))          # qd=1, no body
    out.append(("label-past-end", q(b"\x3fzz", tail=b"")))
    out.append(("name-unterminated", hdr + b"\x03foo"))
    out.append(("pointer-self", q(b"\xc0\x0c")))
    out.append(("pointer-forward", q(b"\xc0\x20")))
    out.append(("pointer-truncated", hdr + b"\xc0"))
    out.append(("reserved-label-type", q(b"\x40a\x00")))
    out.append(("label-type-0x80", q(b"\x80a\x00")))
    out.append(("question-truncated", hdr + b"\x01a\x00\x00\x01"))
    out.append(("trailing-bytes",
                q(b"\x01a\x03foo\x03com\x00") + b"JUNKJUNK"))
    # name assembled past 255 bytes via chained max labels
    out.append(("name-too-long", q((b"\x3f" + b"a" * 63) * 5 + b"\x00")))
    # an answer record whose rdlen runs past the end
    ans_hdr = struct.pack(">HHHHHH", 0x1234, 0x8100, 1, 1, 0, 0)
    out.append(("rdata-past-end",
                ans_hdr + b"\x01a\x00\x00\x01\x00\x01"
                + b"\x01a\x00\x00\x01\x00\x01\x00\x00\x00\x3c\x00\xff"
                + b"\x7f"))
    out.append(("srv-rdata-short",
                ans_hdr + b"\x01a\x00\x00\x21\x00\x01"
                + b"\x01a\x00\x00\x21\x00\x01\x00\x00\x00\x3c\x00\x02"
                + b"\x00\x00"))
    out.append(("soa-rdata-short",
                ans_hdr + b"\x01a\x00\x00\x06\x00\x01"
                + b"\x01a\x00\x00\x06\x00\x01\x00\x00\x00\x3c\x00\x03"
                + b"\x00\x00\x00"))
    out.append(("txt-string-past-rdata",
                ans_hdr + b"\x01a\x00\x00\x10\x00\x01"
                + b"\x01a\x00\x00\x10\x00\x01\x00\x00\x00\x3c\x00\x02"
                + b"\x08a"))
    out.append(("qdcount-huge",
                struct.pack(">HHHHHH", 1, 0x0100, 0xFFFF, 0, 0, 0)
                + b"\x01a\x00\x00\x01\x00\x01"))
    out.append(("arcount-huge",
                struct.pack(">HHHHHH", 1, 0x0100, 1, 0, 0, 0xFFFF)
                + b"\x01a\x03foo\x03com\x00\x00\x01\x00\x01"))
    out.append(("bad-utf8-label", q(b"\x04\xff\xfe\xfd\xfc\x00")))
    out.append(("null-bytes-64", b"\x00" * 64))
    out.append(("all-0xff-64", b"\xff" * 64))
    # seeded fuzz: random frames across the size range the UDP lane
    # accepts; deterministic so the checked-in corpus never drifts
    rng = random.Random(seed)
    for i in range(200):
        n = rng.choice((3, 7, 11, 12, 13, 17, 25, 40, 80, 200, 512))
        out.append((f"fuzz-{i:03d}",
                    bytes(rng.randrange(256) for _ in range(n))))
    # fuzz variants that keep a plausible header so count-walking code
    # is reached with garbage bodies
    for i in range(100):
        body = bytes(rng.randrange(256)
                     for _ in range(rng.randrange(1, 64)))
        counts = struct.pack(">HHHH", rng.randrange(4), rng.randrange(3),
                             rng.randrange(3), rng.randrange(3))
        out.append((f"fuzz-hdr-{i:03d}",
                    struct.pack(">HH", rng.randrange(65536), 0x0100)
                    + counts + body))
    return out


def write_corpus(path: str, seed: int = 1337) -> int:
    """Write the corpus as length-prefixed frames plus a .manifest
    sidecar of labels (one per line, same order)."""
    frames = malformed_frames(seed)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        for _, frame in frames:
            f.write(struct.pack(">H", len(frame)) + frame)
    with open(path + ".manifest", "w") as f:
        for label, _ in frames:
            f.write(label + "\n")
    return len(frames)


def read_corpus(path: str) -> List[Tuple[str, bytes]]:
    labels: List[str] = []
    manifest = path + ".manifest"
    if os.path.exists(manifest):
        with open(manifest) as f:
            labels = [ln.strip() for ln in f if ln.strip()]
    frames: List[bytes] = []
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    while off + 2 <= len(data):
        (n,) = struct.unpack_from(">H", data, off)
        off += 2
        frames.append(data[off:off + n])
        off += n
    return [(labels[i] if i < len(labels) else f"frame-{i}", fr)
            for i, fr in enumerate(frames)]


# ---------------------------------------------------------------------------
# Frame builders for the non-malformed categories


def _edns_edge_frames(domain: str, rng: random.Random) -> List[bytes]:
    """EDNS edge cases: legal-but-weird OPT postures.  All must be
    answered (possibly FORMERR/REFUSED) without exceptions."""
    frames = []
    name = f"edns.{domain}"
    for payload in (0, 1, 511, 512, 1232, 4096, 65535):
        msg = make_query(name, Type.A, qid=rng.randrange(1, 65536),
                         edns_payload=None)
        wire = bytearray(msg.encode())
        # hand-assembled OPT so we control every field: root name,
        # TYPE=41, class=payload, ttl carries ext-rcode/version/DO
        wire[10:12] = struct.pack(">H", 1)  # arcount=1
        wire += b"\x00" + struct.pack(">HHI", 41, payload, 0) + b"\x00\x00"
        frames.append(bytes(wire))
    # EDNS version 1 (BADVERS territory), DO bit, unknown option
    for ttl, opts in ((0x00010000, b""), (0x00008000, b""),
                      (0, b"\x00\x0a\x00\x04zzzz")):
        msg = make_query(name, Type.A, qid=rng.randrange(1, 65536),
                         edns_payload=None)
        wire = bytearray(msg.encode())
        wire[10:12] = struct.pack(">H", 1)
        wire += (b"\x00" + struct.pack(">HHI", 41, 1232, ttl)
                 + struct.pack(">H", len(opts)) + opts)
        frames.append(bytes(wire))
    # two OPT records (illegal per RFC 6891 — server may FORMERR)
    msg = make_query(name, Type.A, qid=rng.randrange(1, 65536),
                     edns_payload=None)
    wire = bytearray(msg.encode())
    wire[10:12] = struct.pack(">H", 2)
    opt = b"\x00" + struct.pack(">HHI", 41, 1232, 0) + b"\x00\x00"
    wire += opt + opt
    frames.append(bytes(wire))
    return frames


_B32 = "abcdefghijklmnopqrstuvwxyz234567"


def _rand_name(rng: random.Random, domain: str) -> str:
    label = "".join(rng.choice(_B32) for _ in range(12))
    return f"{label}.{domain}"


# ---------------------------------------------------------------------------
# Flows


class Flow:
    """One client flow: a UDP socket bound to its own source address
    (distinct 4-tuple), connected to the server so send() is one
    syscall, with per-qid category tracking for reply attribution."""

    __slots__ = ("sock", "src", "category", "qids", "next_qid")

    def __init__(self, server: Tuple[str, int], src_ip: str,
                 category: str) -> None:
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setblocking(False)
        try:
            self.sock.bind((src_ip, 0))
        except OSError:
            # non-Linux fallback: ephemeral port on the default source
            self.sock.bind(("127.0.0.1", 0))
        self.sock.connect(server)
        self.src = self.sock.getsockname()
        self.category = category
        self.qids: Dict[int, str] = {}
        self.next_qid = 1

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def _new_report() -> Dict[str, Dict[str, int]]:
    return {cat: {"sent": 0, "answered": 0, "refused": 0, "formerr": 0,
                  "slipped": 0, "dropped": 0} for cat in CATEGORIES}


def _classify(reply: bytes) -> str:
    if len(reply) < 12:
        return "answered"   # weird but it IS a reply
    flags = (reply[2] << 8) | reply[3]
    rcode = flags & 0xF
    ancount = (reply[6] << 8) | reply[7]
    if (flags & 0x0200) and ancount == 0 and rcode == 0:
        return "slipped"    # TC=1, empty: the RRL slip
    if rcode == 1:
        return "formerr"
    if rcode == 5:
        return "refused"
    return "answered"


def blast(host: str, port: int, *, duration: float = 10.0,
          flows: int = 64, mix: Optional[Dict[str, float]] = None,
          names: Optional[Sequence[str]] = None,
          domain: str = "foo.com", qps: int = 0,
          seed: int = 7, corpus: Optional[List[Tuple[str, bytes]]] = None,
          ) -> Dict[str, object]:
    """Run the hostile load for *duration* seconds; returns the report.

    ``qps=0`` means unpaced (as fast as the box sends).  ``names`` is
    the realistic name population for the legit/spoof categories
    (defaults to ``w{0..7}.{domain}``)."""
    mix = dict(mix or DEFAULT_MIX)
    total_w = sum(mix.get(c, 0.0) for c in CATEGORIES) or 1.0
    weights = [mix.get(c, 0.0) / total_w for c in CATEGORIES]
    rng = random.Random(seed)
    names = list(names or [f"w{i}.{domain}" for i in range(8)])
    corpus_frames = [fr for _, fr in (corpus or malformed_frames())]
    edns_frames = _edns_edge_frames(domain, rng)
    server = (host, port)

    # flow population: spoof flows get hostile-prefix sources; the
    # rest draw from a wider 127/8 spread (distinct 4-tuples but not
    # concentrated in one prefix, like real eyeballs)
    flow_objs: List[Flow] = []
    n_spoof = max(1, int(flows * weights[CATEGORIES.index("spoof")])) \
        if weights[CATEGORIES.index("spoof")] > 0 else 0
    for i in range(flows):
        if i < n_spoof:
            pfx = HOSTILE_PREFIXES[i % len(HOSTILE_PREFIXES)]
            src = f"{pfx}.{(i % 253) + 2}"
            cat = "spoof"
        else:
            src = f"127.{(i % 31) + 100}.{(i // 31) % 256}." \
                  f"{(i % 253) + 2}"
            cat = "any"
        flow_objs.append(Flow(server, src, cat))

    sel = selectors.DefaultSelector()
    for fl in flow_objs:
        sel.register(fl.sock, selectors.EVENT_READ, fl)

    report = _new_report()
    sent_total = 0
    t0 = time.monotonic()
    deadline = t0 + duration
    next_send = t0
    interval = (1.0 / qps) if qps > 0 else 0.0
    burst = 32
    other_cats = [c for c in CATEGORIES if c != "spoof"]
    other_w = [mix.get(c, 0.0) for c in other_cats]
    if sum(other_w) <= 0:
        other_w = [1.0] * len(other_cats)
    fi = 0

    def build(cat: str, fl: Flow) -> bytes:
        if cat in ("legit", "spoof"):
            qtype = rng.choices([t for t, _ in QTYPE_MIX],
                                weights=[w for _, w in QTYPE_MIX])[0]
            name = rng.choice(names)
            qid = fl.next_qid
            fl.next_qid = (fl.next_qid % 65535) + 1
            fl.qids[qid] = cat
            return make_query(name, qtype, qid=qid,
                              edns_payload=(1232 if rng.random() < 0.8
                                            else None)).encode()
        if cat == "random":
            qid = fl.next_qid
            fl.next_qid = (fl.next_qid % 65535) + 1
            fl.qids[qid] = cat
            return make_query(_rand_name(rng, domain), Type.A,
                              qid=qid).encode()
        if cat == "malformed":
            frame = rng.choice(corpus_frames)
            if len(frame) >= 2:
                fl.qids[(frame[0] << 8) | frame[1]] = cat
            return frame
        if cat == "edns":
            frame = rng.choice(edns_frames)
            fl.qids[(frame[0] << 8) | frame[1]] = cat
            return frame
        # oversized: a junk datagram far over MAX_EDNS_PAYLOAD
        return b"\x13\x37" + b"\xab" * 8190

    def drain(timeout: float = 0.0) -> None:
        for key, _ in sel.select(timeout):
            fl: Flow = key.data
            for _ in range(64):
                try:
                    reply = fl.sock.recv(65535)
                except (BlockingIOError, InterruptedError):
                    break
                except OSError:
                    break
                cat = "oversized"
                if len(reply) >= 2:
                    qid = (reply[0] << 8) | reply[1]
                    cat = fl.qids.pop(qid, None) or \
                        ("spoof" if fl.category == "spoof" else "legit")
                report[cat][_classify(reply)] += 1

    while True:
        now = time.monotonic()
        if now >= deadline:
            break
        if interval and now < next_send:
            drain(min(next_send - now, deadline - now))
            continue
        for _ in range(burst):
            fl = flow_objs[fi]
            fi = (fi + 1) % len(flow_objs)
            if fl.category == "spoof":
                cat = "spoof"
            else:
                cat = rng.choices(other_cats, weights=other_w)[0]
            frame = build(cat, fl)
            try:
                fl.sock.send(frame)
            except OSError:
                continue    # buffer full / oversized rejected locally
            report[cat]["sent"] += 1
            sent_total += 1
            if interval:
                next_send += interval
        drain(0.0)
    # grace drain for stragglers
    end = time.monotonic() + 0.25
    while time.monotonic() < end:
        drain(0.05)
    elapsed = time.monotonic() - t0

    for cat, row in report.items():
        row["dropped"] = max(0, row["sent"] - row["answered"]
                             - row["refused"] - row["formerr"]
                             - row["slipped"])
    prefixes = len({fl.src[0].rsplit(".", 1)[0] for fl in flow_objs})
    for fl in flow_objs:
        sel.unregister(fl.sock)
        fl.close()
    sel.close()
    return {
        "duration_s": round(elapsed, 3),
        "flows": flows,
        "mix": {c: round(w, 4) for c, w in zip(CATEGORIES, weights)},
        "hostile_qps": round(sent_total / elapsed, 1) if elapsed else 0.0,
        "sent": sent_total,
        # population shape (same keys tools/population.py exports, so
        # consumers can describe ANY harness run uniformly): hostile
        # flows are one identity per socket, uniform name draw, no NAT
        "population": {"identities": flows, "prefixes": prefixes,
                       "zipf_s": None, "nat_fan_in": 1},
        "categories": report,
    }


def legit_probe(host: str, port: int, *, duration: float = 5.0,
                names: Optional[Sequence[str]] = None,
                domain: str = "foo.com", timeout: float = 0.5,
                qps: int = 0) -> Dict[str, float]:
    """Closed-loop legit client from 127.0.0.1 (NOT a hostile prefix):
    one query at a time, waits for each answer — the goodput
    measurement the hostile bench axis compares against its no-flood
    control.  ``qps`` paces the offered load (0 = as fast as answers
    come back); pace it *below* the server's RRL per-prefix limit, or
    the probe measures its own rate limiting instead of the flood's
    collateral damage.  Returns {qps, answered, sent, timeouts,
    answered_ratio}."""
    names = list(names or [f"w{i}.{domain}" for i in range(8)])
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.connect((host, port))
    sock.settimeout(timeout)
    sent = answered = timeouts = 0
    qid = 1
    t0 = time.monotonic()
    deadline = t0 + duration
    interval = (1.0 / qps) if qps > 0 else 0.0
    next_send = t0
    try:
        while True:
            now = time.monotonic()
            if now >= deadline:
                break
            if interval and now < next_send:
                time.sleep(min(next_send - now, deadline - now))
                continue
            next_send += interval
            name = names[sent % len(names)]
            wire = make_query(name, Type.A, qid=qid).encode()
            qid = (qid % 65535) + 1
            sock.send(wire)
            sent += 1
            try:
                reply = sock.recv(65535)
            except socket.timeout:
                timeouts += 1
                continue
            if len(reply) >= 12 and (reply[3] & 0xF) == 0:
                answered += 1
    finally:
        sock.close()
    elapsed = time.monotonic() - t0
    return {"qps": round(answered / elapsed, 1) if elapsed else 0.0,
            "sent": sent, "answered": answered, "timeouts": timeouts,
            "answered_ratio": round(answered / sent, 4) if sent else 0.0}


def parse_mix(text: str) -> Dict[str, float]:
    mix: Dict[str, float] = {}
    for part in text.split(","):
        if not part.strip():
            continue
        cat, _, w = part.partition("=")
        if cat.strip() not in CATEGORIES:
            raise ValueError(f"unknown category {cat.strip()!r} "
                             f"(have {', '.join(CATEGORIES)})")
        mix[cat.strip()] = float(w or 1.0)
    return mix


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="adversarial multi-flow DNS load harness")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--flows", type=int, default=64)
    ap.add_argument("--qps", type=int, default=0,
                    help="paced send rate (0 = unpaced)")
    ap.add_argument("--mix", type=parse_mix, default=None,
                    help="e.g. legit=0.2,spoof=0.5,malformed=0.3")
    ap.add_argument("--domain", default="foo.com")
    ap.add_argument("--names", default=None,
                    help="comma-separated realistic name population")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--write-corpus", metavar="PATH", default=None,
                    help="write the malformed corpus + manifest and exit")
    args = ap.parse_args(argv)

    if args.write_corpus:
        n = write_corpus(args.write_corpus)
        print(f"wrote {n} frames to {args.write_corpus}", file=sys.stderr)
        return 0
    if args.port is None:
        ap.error("--port is required")
    names = args.names.split(",") if args.names else None
    report = blast(args.host, args.port, duration=args.duration,
                   flows=args.flows, mix=args.mix, names=names,
                   domain=args.domain, qps=args.qps, seed=args.seed)
    json.dump(report, sys.stdout, indent=2)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
