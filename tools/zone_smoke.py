#!/usr/bin/env python3
"""Zone-scale smoke: the million-name representation's invariants, end
to end, as a CI gate (ISSUE 7).

Builds a synthetic mirror at a small CONTROL size and at the smoke size
(``BINDER_ZONE_NAMES``, default 100k; ``make ci`` runs a trimmed 20k),
applies a mutation burst + watch storm through the real
mirror → invalidate → precompile chain (tools/zone_probe.py), and
asserts:

- single-name rebuild latency is independent of zone size
  (p50 at the smoke size within ``LAT_RATIO_MAX`` of the control —
  O(delta), not O(zone));
- every re-rendered compiled answer is byte-identical to a fresh
  engine render (answers stay engine-parity through the compact
  representation);
- the watch storm drains without wedging (bounded backpressure);
- the chunked session rebuild never stalls the event loop past the
  loop-lag watchdog threshold, and lookups keep serving throughout;
- the in-process metrics surface passes ``validate_mirror_metrics``
  (TYPE + label pins for the ``binder_mirror_*`` family).

Prints one JSON summary line; exit 0 == all invariants held.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from binder_tpu.metrics.collector import MetricsCollector  # noqa: E402
from binder_tpu.server import BinderServer  # noqa: E402
from binder_tpu.store import FakeStore, MirrorCache  # noqa: E402
from tools.lint import validate_mirror_metrics  # noqa: E402
from tools.zone_probe import probe  # noqa: E402

CONTROL = int(os.environ.get("BINDER_ZONE_CONTROL", "2000"))
SMOKE = int(os.environ.get("BINDER_ZONE_NAMES", "100000"))
#: p50 mutation latency at the smoke size may be at most this multiple
#: of the control's — generous against CI noise while still failing
#: loudly on anything O(zone) (a linear path would show up as ~SMOKE /
#: CONTROL, i.e. 50x)
LAT_RATIO_MAX = 4.0
#: the loop-lag watchdog's stall threshold (introspect/watchdog.py)
STALL_THRESHOLD_MS = 250.0


def scrape_mirror_metrics() -> list:
    """Build a collector-wired server over a small mirror and validate
    the binder_mirror_* / zone-scale exposition pins."""
    collector = MetricsCollector()
    store = FakeStore()
    store.put_json("/com/smoke/web",
                   {"type": "host", "host": {"address": "10.0.0.1"}})
    cache = MirrorCache(store, "smoke.com", collector=collector)
    store.start_session()
    BinderServer(zk_cache=cache, dns_domain="smoke.com",
                 collector=collector, cache_size=16)
    return validate_mirror_metrics(collector.expose())


def main() -> int:
    failures = []
    results = {"control_names": CONTROL, "smoke_names": SMOKE}

    control = probe(CONTROL, mutations=100,
                    storm=max(100, CONTROL // 4))
    smoke = probe(SMOKE, mutations=150, storm=max(500, SMOKE // 20))
    results["control"] = control
    results["smoke"] = smoke

    ratio = smoke["mutation_p50_us"] / max(1e-9,
                                           control["mutation_p50_us"])
    results["mutation_p50_ratio"] = round(ratio, 2)
    if ratio > LAT_RATIO_MAX:
        failures.append(
            f"mutation latency scales with zone size: p50 "
            f"{smoke['mutation_p50_us']}us at {SMOKE} names vs "
            f"{control['mutation_p50_us']}us at {CONTROL} "
            f"(ratio {ratio:.1f} > {LAT_RATIO_MAX})")

    parity = control["parity_failures"] + smoke["parity_failures"]
    if parity:
        failures.append(f"{parity} re-rendered answer(s) diverged "
                        "from a fresh engine render")

    if smoke["rebuild_max_loop_lag_ms"] > STALL_THRESHOLD_MS:
        failures.append(
            f"chunked rebuild stalled the loop "
            f"{smoke['rebuild_max_loop_lag_ms']}ms "
            f"(watchdog threshold {STALL_THRESHOLD_MS}ms)")
    if smoke["rebuild_miss_mid"]:
        failures.append(
            f"{smoke['rebuild_miss_mid']} lookup(s) went dark during "
            "the chunked rebuild (serving must continue)")
    if smoke["rebuild_chunks"] < 2:
        failures.append("rebuild at smoke size did not chunk")

    # storm drained (probe would have hung otherwise) — pin the figure
    results["storm_recovery_s"] = smoke["storm_recovery_s"]

    lint_errs = scrape_mirror_metrics()
    if lint_errs:
        failures.append("mirror metrics exposition: "
                        + "; ".join(lint_errs[:5]))

    results["failures"] = failures
    results["ok"] = not failures
    print(json.dumps(results))
    if failures:
        for f in failures:
            print("zone-smoke FAIL:", f, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
