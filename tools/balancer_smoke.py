#!/usr/bin/env python3
"""Balancer-fronted end-to-end smoke: direct return + backend churn.

Boots the REAL mbalancer binary (native/balancer) in front of two
in-process backends speaking the balancer socket protocol, then, while
driving continuous UDP load at the balancer's client port, asserts the
compatibility lane's operational invariants end to end
(docs/balancer-protocol.md, ISSUE 18):

- the direct-return negotiation completes (fd passed to every
  connected backend, ``direct_forwards`` advancing — replies leave on
  the balancer's own client socket without re-entering it);
- a mid-stream backend departure (stop + socket unlink, the SIGTERM
  semantics) costs no client-visible timeouts: every query is
  answered within its retry budget while affinity is re-pointed at
  the survivor;
- the departed instance coming BACK is re-adopted on the next scan:
  connection re-established, direct return renegotiated
  (``fd_passes`` advances past the initial pass count), both
  backends healthy;
- the stats-socket counters stay monotonic across the churn — stage
  cycles/ops, ``udp_queries``, ``direct_forwards``, and the recvmmsg
  batch histogram never regress (a balancer that resets attribution
  on backend loss would corrupt every cross-incident comparison).

Run via ``make balancer-smoke`` (30 s) or set
``BINDER_BALANCER_SECONDS``.  Prints one JSON summary line; exit 0 ==
all invariants held.
"""
import asyncio
import json
import os
import socket
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from binder_tpu.dns import Message, Rcode, Type, make_query  # noqa: E402
from binder_tpu.metrics.collector import MetricsCollector  # noqa: E402
from binder_tpu.server import BinderServer  # noqa: E402
from binder_tpu.store import FakeStore, MirrorCache  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BALANCER = os.environ.get("BINDER_BALANCER") or os.path.join(
    ROOT, "native", "build", "mbalancer")
DOMAIN = "balsmoke.test"


class Violation(Exception):
    pass


def _fixture(tag: int) -> MirrorCache:
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    # the answer address encodes which backend served the query, so
    # the failover assertion can watch affinity move
    store.put_json("/test/balsmoke/web",
                   {"type": "host", "host": {"address": f"10.44.0.{tag}"}})
    store.start_session()
    return cache


async def _start_backend(sockdir: str, instance: int) -> BinderServer:
    server = BinderServer(
        zk_cache=_fixture(instance), dns_domain=DOMAIN,
        datacenter_name="dc0", host="127.0.0.1", port=0,
        balancer_socket=os.path.join(sockdir, str(instance)),
        collector=MetricsCollector(), query_log=False)
    await server.start()
    return server


async def _start_balancer(sockdir: str):
    proc = await asyncio.create_subprocess_exec(
        BALANCER, "-d", sockdir, "-p", "0", "-b", "127.0.0.1",
        "-s", "150", "-c", "0",
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.DEVNULL)
    line = await asyncio.wait_for(proc.stdout.readline(), 30)
    if not line.startswith(b"PORT "):
        raise Violation(f"mbalancer announce: {line!r}")
    return proc, int(line.split()[1])


def _read_stats(sockdir: str) -> dict:
    c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    c.settimeout(5)
    try:
        c.connect(os.path.join(sockdir, ".balancer.stats"))
        buf = b""
        while True:
            chunk = c.recv(4096)
            if not chunk:
                break
            buf += chunk
    finally:
        c.close()
    return json.loads(buf)


def _monotone_keys(stats: dict) -> dict:
    """The counters that must never regress across backend churn."""
    flat = {"udp_queries": stats["udp_queries"],
            "tcp_queries": stats["tcp_queries"],
            "fd_passes": stats["fd_passes"],
            "direct_forwards": stats["direct_forwards"],
            "syscalls": stats["syscalls"]}
    for i, c in enumerate(stats.get("udp_batch_cells", [])):
        flat[f"udp_batch_cells[{i}]"] = c
    for stage, cell in (stats.get("stage_cycles") or {}).items():
        flat[f"stage.{stage}.cycles"] = cell.get("cycles", 0)
        flat[f"stage.{stage}.ops"] = cell.get("ops", 0)
    return flat


def _check_monotone(prev: dict, cur: dict, where: str) -> None:
    for k, v in cur.items():
        if k in prev and v < prev[k]:
            raise Violation(
                f"counter {k} regressed {prev[k]} -> {v} ({where})")


async def _ask(port: int, qid: int, timeout: float = 2.0):
    """One query with a 3-try retry budget on a fresh socket.  A lost
    in-flight packet during the kill window costs a retry; a query
    that exhausts the budget is the client-visible timeout the smoke
    exists to rule out."""
    loop = asyncio.get_running_loop()
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.setblocking(False)
    sock.connect(("127.0.0.1", port))
    wire = make_query(f"web.{DOMAIN}", Type.A, qid=qid).encode()
    try:
        for attempt in range(3):
            sock.send(wire)
            try:
                data = await asyncio.wait_for(
                    loop.sock_recv(sock, 4096), timeout)
                return data, attempt
            except asyncio.TimeoutError:
                continue
        raise Violation(f"query qid={qid} unanswered after 3 tries "
                        f"(client-visible timeout)")
    finally:
        sock.close()


async def run_incident(duration: float) -> dict:
    sockdir = tempfile.mkdtemp(prefix="bal-smoke-")
    b0 = await _start_backend(sockdir, 1)
    b1 = await _start_backend(sockdir, 2)
    backends = {1: b0, 2: b1}
    proc, port = await _start_balancer(sockdir)
    stats_out = {"queries": 0, "retries": 0}
    try:
        # wait for both connections + the direct-return fd passes
        deadline = time.monotonic() + 15
        while True:
            try:
                stats = _read_stats(sockdir)
                bes = stats.get("backends", [])
                if (len(bes) == 2 and all(b["healthy"] for b in bes)
                        and all(b.get("direct") for b in bes)):
                    break
            except (OSError, ValueError):
                pass
            if time.monotonic() > deadline:
                raise Violation("backends never adopted direct return")
            await asyncio.sleep(0.1)
        fd_passes0 = stats["fd_passes"]
        if fd_passes0 < 2:
            raise Violation(f"expected >=2 fd passes, got {fd_passes0}")

        kill_at = max(1.0, duration * 0.35)
        revive_at = max(2.0, duration * 0.6)
        t0 = time.monotonic()
        t_end = t0 + duration
        prev = _monotone_keys(stats)
        served_tags = set()
        killed = revived = None
        i = 0
        while time.monotonic() < t_end:
            i += 1
            now = time.monotonic() - t0
            data, retries = await _ask(port, qid=(i % 0xFFFF) + 1)
            stats_out["queries"] += 1
            stats_out["retries"] += retries
            msg = Message.decode(data)
            if msg.rcode != Rcode.NOERROR or not msg.answers:
                raise Violation(f"bad answer rcode={msg.rcode}")
            tag = int(msg.answers[0].address.rsplit(".", 1)[1])
            served_tags.add(tag)

            if killed is None and now >= kill_at:
                # mid-stream departure of the backend that owns the
                # load: SIGTERM semantics = stop + unlink the socket
                killed = tag
                victim = backends[tag]
                path = victim.balancer_socket
                await victim.stop()
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
            elif killed is not None and revived is None \
                    and now >= revive_at:
                # the departed instance returns; the next scan must
                # re-adopt it and renegotiate direct return
                backends[killed] = await _start_backend(sockdir, killed)
                revived = killed

            if i % 25 == 0:
                cur = _monotone_keys(_read_stats(sockdir))
                _check_monotone(prev, cur, f"t+{now:.1f}s")
                prev = cur
            await asyncio.sleep(duration / 2000.0)

        if killed is None:
            raise Violation("duration too short: kill never happened")
        if len(served_tags) < 2:
            raise Violation(f"affinity never moved off backend "
                            f"{killed} after its departure")

        # post-churn: both backends healthy, direct return renegotiated
        # on the revived connection, counters still monotone
        deadline = time.monotonic() + 10
        while True:
            stats = _read_stats(sockdir)
            bes = stats.get("backends", [])
            if (revived is not None and len(bes) == 2
                    and all(b["healthy"] for b in bes)
                    and all(b.get("direct") for b in bes)):
                break
            if time.monotonic() > deadline:
                raise Violation(f"revived backend not re-adopted: "
                                f"{bes}")
            await asyncio.sleep(0.2)
        _check_monotone(prev, _monotone_keys(stats), "post-churn")
        if stats["fd_passes"] <= fd_passes0:
            raise Violation("direct return not renegotiated after "
                            "backend revival")
        if stats["direct_forwards"] <= 0:
            raise Violation("no direct-return forwards recorded")

        stats_out.update({
            "duration_s": duration,
            "killed_backend": killed,
            "served_tags": sorted(served_tags),
            "fd_passes": stats["fd_passes"],
            "direct_forwards": stats["direct_forwards"],
            "udp_queries": stats["udp_queries"],
            "syscalls_per_query": round(
                stats["syscalls"] / stats["udp_queries"], 3)
            if stats["udp_queries"] else None,
        })
        return stats_out
    finally:
        proc.kill()
        await proc.wait()
        for b in backends.values():
            try:
                await b.stop()
            except Exception:
                pass


def run_smoke(duration: float = None) -> dict:
    if duration is None:
        duration = float(os.environ.get("BINDER_BALANCER_SECONDS", "30"))
    return asyncio.run(run_incident(duration))


def main() -> int:
    if not os.path.exists(BALANCER):
        print(json.dumps({"ok": False,
                          "error": "mbalancer not built (make -C native)"}))
        return 1
    try:
        stats = run_smoke()
    except Violation as e:
        print(json.dumps({"ok": False, "violation": str(e)}))
        return 1
    print(json.dumps({"ok": True, **stats}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
