#!/usr/bin/env python3
"""First-party Python lint gate (the jsstyle/javascriptlint analog).

The reference gates CI on vendored linters (`make check` runs jsstyle +
javascriptlint, reference Jenkinsfile:37-40, deps/jsstyle,
deps/javascriptlint); this image ships no Python linter, so this tool
implements the high-signal, zero-false-positive subset used by `make
check`.  Zero findings is the passing state; every rule here is cheap to
satisfy and each finding is a real smell:

  unused-import        imported name never referenced in the module
  import-shadowed      def/class rebinds an imported name
  bare-except          `except:` catches SystemExit/KeyboardInterrupt
  duplicate-dict-key   constant key repeated in a dict literal
  f-string-no-placeholder  f-prefix on a string with no {…}
  is-literal           `is` / `is not` against a str/number literal
  mutable-default      def f(x=[]) / f(x={}) / f(x=set())
  assert-tuple         assert (cond, "msg") — always true

Usage: python tools/lint.py <paths...>   (directories are walked for .py
files; explicit files are linted regardless of extension so bin/ scripts
can be covered).
"""
import ast
import os
import sys


class Finding:
    def __init__(self, path, line, rule, msg):
        self.path = path
        self.line = line
        self.rule = rule
        self.msg = msg

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}"


def iter_strings(node):
    """All string constants syntactically inside `node` (docstrings and
    __all__ entries count as usage for re-export barrels)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


class Linter(ast.NodeVisitor):
    def __init__(self, path, tree, source):
        self.path = path
        self.tree = tree
        self.source = source
        self.findings = []

    def add(self, node, rule, msg):
        self.findings.append(Finding(self.path, node.lineno, rule, msg))

    def run(self):
        self.check_imports()
        self.visit(self.tree)
        return self.findings

    # ---- unused imports / shadowing (module scope) ----

    def check_imports(self):
        # __init__.py imports are re-export surface (the lib/index.js
        # barrel pattern); "unused" is their whole point
        barrel = os.path.basename(self.path) == "__init__.py"
        imported = {}   # name -> (node, reported_name)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    imported.setdefault(name, (node, a.asname or a.name))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    name = a.asname or a.name
                    imported.setdefault(name, (node, name))

        used = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                # handled via the Name at the base of the chain
                pass
        # names mentioned in strings count (docstring references, __all__,
        # typing forward refs)
        strings = set()
        for s in iter_strings(self.tree):
            if len(s) < 200:
                for tok in s.replace(",", " ").replace("'", " ").split():
                    strings.add(tok.strip("\"`()[]{}.:;"))

        redefined = set()
        # module-level defs only: a method or nested function named like
        # an import does not rebind the module-level name
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                if node.name in imported:
                    redefined.add(node.name)
                    self.add(node, "import-shadowed",
                             f"definition of {node.name!r} shadows an "
                             f"import of the same name")

        if barrel:
            return
        for name, (node, reported) in imported.items():
            if name.startswith("_") or name in redefined:
                continue
            if name not in used and name not in strings:
                self.add(node, "unused-import",
                         f"{reported!r} imported but unused")

    # ---- node-local rules ----

    def visit_ExceptHandler(self, node):
        if node.type is None:
            self.add(node, "bare-except",
                     "bare `except:` also catches SystemExit/"
                     "KeyboardInterrupt; use `except Exception:`")
        self.generic_visit(node)

    def visit_Dict(self, node):
        seen = {}
        for k in node.keys:
            if isinstance(k, ast.Constant):
                try:
                    hash(k.value)
                except TypeError:
                    continue
                if k.value in seen:
                    self.add(k, "duplicate-dict-key",
                             f"duplicate dict key {k.value!r}")
                seen[k.value] = True
        self.generic_visit(node)

    def visit_JoinedStr(self, node):
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.add(node, "f-string-no-placeholder",
                     "f-string has no placeholders")
        self.generic_visit(node)

    def visit_FormattedValue(self, node):
        # format specs (f"{x:>3}") are themselves JoinedStr nodes holding
        # only Constants; don't descend or every spec is a false positive
        self.visit(node.value)

    def visit_Compare(self, node):
        # chained comparisons: op[i] compares comparators[i-1] (or .left
        # for i == 0) with comparators[i]
        lefts = [node.left] + list(node.comparators[:-1])
        for left, op, comp in zip(lefts, node.ops, node.comparators):
            if isinstance(op, (ast.Is, ast.IsNot)):
                operands = [comp, left]
                for o in operands:
                    if isinstance(o, ast.Constant) and isinstance(
                            o.value, (str, int, float, bytes)) and \
                            not isinstance(o.value, bool):
                        self.add(node, "is-literal",
                                 "`is` comparison with a literal; "
                                 "use == / !=")
                        break
        self.generic_visit(node)

    def _check_defaults(self, node):
        for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call)
                    and isinstance(d.func, ast.Name)
                    and d.func.id in ("list", "dict", "set")
                    and not d.args and not d.keywords):
                self.add(d, "mutable-default",
                         "mutable default argument; use None and "
                         "initialize inside")

    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_Assert(self, node):
        if isinstance(node.test, ast.Tuple) and node.test.elts:
            self.add(node, "assert-tuple",
                     "assert on a non-empty tuple is always true "
                     "(did you mean `assert cond, msg`?)")
        self.generic_visit(node)


def lint_file(path):
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except (OSError, UnicodeDecodeError) as e:
        return [Finding(path, 0, "unreadable", str(e))]
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "syntax-error", e.msg)]
    return Linter(path, tree, source).run()


def is_python_script(path):
    if path.endswith(".py"):
        return True
    try:
        with open(path, "rb") as f:
            head = f.read(64)
        return head.startswith(b"#!") and b"python" in head.splitlines()[0]
    except OSError:
        return False


def collect(paths):
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git", "build")]
                for fn in sorted(files):
                    full = os.path.join(root, fn)
                    if is_python_script(full):
                        out.append(full)
        else:
            if is_python_script(p):
                out.append(p)
    return out


def main(argv):
    paths = argv or ["binder_tpu", "tests", "bin", "tools",
                     "bench.py", "bench_impl.py", "__graft_entry__.py"]
    files = collect(paths)
    if not files:
        print("lint: no files found", file=sys.stderr)
        return 2
    findings = []
    for path in files:
        findings.extend(lint_file(path))
    for f in findings:
        print(f)
    if findings:
        print(f"lint: {len(findings)} finding(s) in {len(files)} files",
              file=sys.stderr)
        return 1
    print(f"lint: ok ({len(files)} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
